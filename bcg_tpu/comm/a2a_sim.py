"""A2A-Sim protocol: synchronous, idealized agent-to-agent messaging.

Behavioural clone of the reference ``a2a_sim.py``:

* static undirected graph, neighbour-only routing with validation
* dual payload — structured :class:`Decision` + free-text reasoning capped
  at 500 chars
* per-round buffered delivery; all round-t messages arrive before t+1
* duplicate suppression keyed on (sender, receiver, round, phase, timestamp)
* inbox ordering by (sender_id, timestamp)

Improvement over the reference: the orchestrator actually calls
``clear_round_buffer`` each round (the reference defines it at
a2a_sim.py:235-244 but never calls it, so buffers grow for the whole run).
The aggregate message count survives clearing via a per-round counter.
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum
from typing import Any, Dict, List, Optional, Set

from bcg_tpu.comm.protocol import CommunicationProtocol, Message, ProtocolClient

REASONING_CHAR_LIMIT = 500  # a2a_sim.py:69-73


def truncate_reasoning(text: str) -> str:
    """The protocol's reasoning cap (reference a2a_sim.py:69-73) — the
    single definition both the message type and the SPMD exchange path
    use, so the two delivery paths stay byte-identical."""
    if len(text) > REASONING_CHAR_LIMIT:
        return text[: REASONING_CHAR_LIMIT - 3] + "..."
    return text


class Phase(str, Enum):
    """Protocol phases (reference a2a_sim.py:20-26)."""

    PROPOSE = "propose"
    PREPARE = "prepare"
    COMMIT = "commit"
    CUSTOM = "custom"


class DecisionType(str, Enum):
    """Structured decision kinds (reference a2a_sim.py:28-32)."""

    VALUE = "value"
    VOTE = "vote"
    ABSTAIN = "abstain"


@dataclass
class Decision:
    """Machine-readable action part of a message (reference a2a_sim.py:35-46)."""

    type: str
    value: Any

    def to_dict(self) -> Dict[str, Any]:
        return {"type": self.type, "value": self.value}

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Decision":
        return cls(type=data["type"], value=data["value"])


@dataclass
class A2AMessage(Message):
    """Dual-payload message (reference a2a_sim.py:49-113).

    Carries both a structured decision and the sender's public reasoning;
    the timestamp is a per-sender monotonic counter used for total ordering
    and duplicate suppression.
    """

    sender_id: int
    receiver_id: int
    round: int
    phase: str
    decision: Decision
    reasoning: str
    timestamp: int

    def __post_init__(self):
        self.reasoning = truncate_reasoning(self.reasoning)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "sender_id": self.sender_id,
            "receiver_id": self.receiver_id,
            "round": self.round,
            "phase": self.phase,
            "decision": self.decision.to_dict(),
            "reasoning": self.reasoning,
            "timestamp": self.timestamp,
        }

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "A2AMessage":
        return cls(
            sender_id=data["sender_id"],
            receiver_id=data["receiver_id"],
            round=data["round"],
            phase=data["phase"],
            decision=Decision.from_dict(data["decision"]),
            reasoning=data["reasoning"],
            timestamp=data["timestamp"],
        )

    def _key(self):
        return (self.sender_id, self.receiver_id, self.round, self.phase, self.timestamp)

    def __hash__(self):
        return hash(self._key())

    def __eq__(self, other):
        return isinstance(other, A2AMessage) and self._key() == other._key()


class A2ASimProtocol(CommunicationProtocol):
    """Round-buffered router over a static graph (reference a2a_sim.py:116-298)."""

    def __init__(self, num_agents: int, topology: Dict[int, List[int]]):
        super().__init__(num_agents, topology)
        # round -> receiver_id -> inbox list
        self.message_buffer: Dict[int, Dict[int, List[A2AMessage]]] = {}
        self.delivered: Set[A2AMessage] = set()
        # round -> count, survives clear_round_buffer so aggregate metrics
        # stay correct even with per-round GC.
        self._round_counts: Dict[int, int] = {}
        self.current_round = 0
        self.current_phase = Phase.PROPOSE.value

    def send_message(self, sender_id: int, receiver_id: int, message: A2AMessage) -> None:
        """Buffer a point-to-point message after neighbour validation and
        duplicate suppression (reference a2a_sim.py:157-181).

        Validation, dedup, and the sent-count are the CHANNEL-INDEPENDENT
        contract; delivery itself goes through :meth:`_route` so
        subclasses (e.g. the lossy channel) override only the routing
        decision.
        """
        if receiver_id not in self.topology.get(sender_id, []):
            raise ValueError(
                f"Agent {sender_id} cannot send to {receiver_id}: not in neighbor set"
            )
        if message in self.delivered:
            return
        self.delivered.add(message)
        self._round_counts[message.round] = self._round_counts.get(message.round, 0) + 1
        self._route(receiver_id, message)

    def _route(self, receiver_id: int, message: A2AMessage) -> None:
        """Deliver into the receiver's inbox for the message's round
        (ideal channel: on time, always)."""
        self.message_buffer.setdefault(message.round, {}).setdefault(
            receiver_id, []
        ).append(message)

    def broadcast_to_neighbors(
        self,
        sender_id: int,
        round: int,
        phase: str,
        decision: Decision,
        reasoning: str,
        timestamp: int,
    ) -> None:
        """Multicast illusion: identical content to every neighbour
        (reference a2a_sim.py:183-210)."""
        for neighbor_id in self.topology.get(sender_id, []):
            self.send_message(
                sender_id,
                neighbor_id,
                A2AMessage(
                    sender_id=sender_id,
                    receiver_id=neighbor_id,
                    round=round,
                    phase=phase,
                    decision=decision,
                    reasoning=reasoning,
                    timestamp=timestamp,
                ),
            )

    def send_per_receiver(
        self,
        sender_id: int,
        round: int,
        phase: str,
        decisions: Dict[int, Decision],
        reasoning: str,
        timestamp: int,
    ) -> None:
        """Equivocating broadcast: a DIFFERENT decision per neighbour
        under one timestamp (the adversary 'broadcasts' once; the
        channel carries receiver-addressed variants).  Neighbours
        without an entry in ``decisions`` get nothing.  Routes through
        :meth:`send_message`, so neighbour validation, dedup, counters,
        and channel overrides (lossy ``_route``) all apply per variant.
        """
        for neighbor_id in self.topology.get(sender_id, []):
            decision = decisions.get(neighbor_id)
            if decision is None:
                continue
            self.send_message(
                sender_id,
                neighbor_id,
                A2AMessage(
                    sender_id=sender_id,
                    receiver_id=neighbor_id,
                    round=round,
                    phase=phase,
                    decision=decision,
                    reasoning=reasoning,
                    timestamp=timestamp,
                ),
            )

    def deliver_messages(self, agent_id: int, round: int) -> List[A2AMessage]:
        """Inbox for (agent, round), ordered by (sender_id, timestamp)
        (reference a2a_sim.py:212-233)."""
        inbox = self.message_buffer.get(round, {}).get(agent_id, [])
        return sorted(inbox, key=lambda m: (m.sender_id, m.timestamp))

    def clear_round_buffer(self, round: int) -> None:
        """GC a completed round's buffers and delivered-set entries."""
        dropped = self.message_buffer.pop(round, None)
        if dropped:
            for inbox in dropped.values():
                for msg in inbox:
                    self.delivered.discard(msg)

    def get_neighbors(self, agent_id: int) -> List[int]:
        return self.topology.get(agent_id, [])

    def set_phase(self, round: int, phase: str) -> None:
        self.current_round = round
        self.current_phase = phase

    def get_message_count(self, round: int) -> int:
        return self._round_counts.get(round, 0)

    def get_total_message_count(self) -> int:
        """Total messages across all rounds (fixes the reference's final-
        round undercount — main.py:804-808 sums ``range(current_round)``
        against 1-indexed round keys)."""
        return sum(self._round_counts.values())

    def reset(self) -> None:
        self.message_buffer.clear()
        self.delivered.clear()
        self._round_counts.clear()
        self.current_round = 0

    # ------------------------------------------------------ checkpointing

    def snapshot(self) -> Dict:
        """JSON-serializable channel state (in-flight buffers + counters)
        for per-round checkpoint/resume.  The delivered set is derived
        from the buffered messages on restore (GC'd rounds' entries were
        already discarded)."""
        return {
            "message_buffer": {
                str(r): {
                    str(a): [m.to_dict() for m in inbox]
                    for a, inbox in boxes.items()
                }
                for r, boxes in self.message_buffer.items()
            },
            "round_counts": {str(r): c for r, c in self._round_counts.items()},
            "current_round": self.current_round,
            "current_phase": self.current_phase,
        }

    def restore(self, blob: Dict) -> None:
        self.message_buffer = {
            int(r): {
                int(a): [A2AMessage.from_dict(d) for d in inbox]
                for a, inbox in boxes.items()
            }
            for r, boxes in blob["message_buffer"].items()
        }
        self.delivered = {
            m
            for boxes in self.message_buffer.values()
            for inbox in boxes.values()
            for m in inbox
        }
        self._round_counts = {
            int(r): c for r, c in blob["round_counts"].items()
        }
        self.current_round = blob["current_round"]
        self.current_phase = blob["current_phase"]

    def create_client(self, agent_id: int) -> "A2ASimClient":
        return A2ASimClient(agent_id=agent_id, protocol=self)


class A2ASimClient(ProtocolClient):
    """Agent-side handle: send, receive, and persistent history H_i
    (reference a2a_sim.py:301-387)."""

    def __init__(self, agent_id: int, protocol: A2ASimProtocol):
        super().__init__(agent_id, protocol)
        self.protocol: A2ASimProtocol = protocol
        self.history: List[Dict[str, Any]] = []
        self._timestamp_counter = 0

    def next_timestamp(self) -> int:
        self._timestamp_counter += 1
        return self._timestamp_counter

    def receive_messages(self, round: int) -> List[A2AMessage]:
        return self.protocol.deliver_messages(self.agent_id, round)

    def send_to_neighbors(
        self, round: int, phase: str = Phase.PROPOSE.value,
        decision: Optional[Decision] = None, reasoning: str = "",
    ) -> None:
        self.protocol.broadcast_to_neighbors(
            sender_id=self.agent_id,
            round=round,
            phase=phase,
            decision=decision,
            reasoning=reasoning,
            timestamp=self.next_timestamp(),
        )

    def send_per_receiver(
        self, round: int, phase: str = Phase.PROPOSE.value,
        decisions: Optional[Dict[int, Decision]] = None, reasoning: str = "",
    ) -> None:
        """Equivocating variant of :meth:`send_to_neighbors`: one
        timestamp, per-neighbour decisions (see the protocol method)."""
        self.protocol.send_per_receiver(
            sender_id=self.agent_id,
            round=round,
            phase=phase,
            decisions=decisions or {},
            reasoning=reasoning,
            timestamp=self.next_timestamp(),
        )

    def update_history(
        self, round: int, inbox: List[A2AMessage], local_state: Dict[str, Any]
    ) -> None:
        self.history.append(
            {
                "round": round,
                "inbox": [m.to_dict() for m in inbox],
                "local_state": local_state,
            }
        )

    def get_neighbors(self) -> List[int]:
        return self.protocol.get_neighbors(self.agent_id)

    def get_history(self) -> List[Dict[str, Any]]:
        return self.history

    def reset(self) -> None:
        self.history.clear()
        self._timestamp_counter = 0
