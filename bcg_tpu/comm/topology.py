"""Network topology builders (reference ``agent_network.py:12-87``).

Adjacency-list graphs consumed by protocols and, on the TPU path, compiled
into dense neighbour masks for the all-gather message exchange
(:mod:`bcg_tpu.parallel.game_step`).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List

import numpy as np


@dataclass
class NetworkTopology:
    num_agents: int
    adjacency_list: Dict[int, List[int]]
    topology_type: str  # fully_connected | ring | grid | custom

    @classmethod
    def fully_connected(cls, num_agents: int) -> "NetworkTopology":
        adj = {i: [j for j in range(num_agents) if j != i] for i in range(num_agents)}
        return cls(num_agents, adj, "fully_connected")

    @classmethod
    def ring(cls, num_agents: int) -> "NetworkTopology":
        adj = {
            i: [(i - 1) % num_agents, (i + 1) % num_agents] for i in range(num_agents)
        }
        return cls(num_agents, adj, "ring")

    @classmethod
    def grid(cls, rows: int, cols: int) -> "NetworkTopology":
        """2-D grid with 4-neighbourhood (reference agent_network.py:47-77 —
        defined there but never reachable from config; wired up here)."""
        adj: Dict[int, List[int]] = {}
        for r in range(rows):
            for c in range(cols):
                idx = r * cols + c
                neighbors = []
                if r > 0:
                    neighbors.append((r - 1) * cols + c)
                if r < rows - 1:
                    neighbors.append((r + 1) * cols + c)
                if c > 0:
                    neighbors.append(r * cols + (c - 1))
                if c < cols - 1:
                    neighbors.append(r * cols + (c + 1))
                adj[idx] = neighbors
        return cls(rows * cols, adj, "grid")

    @classmethod
    def custom(cls, adjacency_list: Dict[int, List[int]]) -> "NetworkTopology":
        return cls(len(adjacency_list), dict(adjacency_list), "custom")

    def neighbor_mask(self) -> np.ndarray:
        """Dense [n, n] bool mask, ``mask[i, j]`` = j is a neighbour of i.

        This is the TPU-native form of the topology: after an
        ``all_gather`` of per-agent (value, vote) tensors over the mesh,
        applying this mask reproduces neighbour-only delivery without any
        per-message routing.
        """
        mask = np.zeros((self.num_agents, self.num_agents), dtype=bool)
        for i, neighbors in self.adjacency_list.items():
            mask[i, neighbors] = True
        return mask

    def receiver_mask(self) -> np.ndarray:
        """Dense [n, n] bool mask in RECEIVER orientation:
        ``mask[i, j]`` = receiver i hears sender j — the transpose of
        :meth:`neighbor_mask`, which is what the delivery paths
        (``runtime/orchestrator._broadcast_receive_spmd`` and the fused
        mega-round's ``parallel/game_step.masked_exchange``) consume.
        Kept as a named surface so the orientation convention lives in
        one place instead of ad-hoc ``.T`` at every call site."""
        return self.neighbor_mask().T.copy()

    @property
    def avg_degree(self) -> float:
        return (
            sum(len(n) for n in self.adjacency_list.values()) / self.num_agents
            if self.num_agents
            else 0.0
        )
