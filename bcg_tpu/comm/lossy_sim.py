"""Lossy/delayed A2A channel — an unreliable-network protocol variant.

The reference's A2A-sim assumes an idealized channel: no loss, delay, or
reordering (reference ``a2a_sim.py:127-132``), and its factory knows only
that one protocol (``protocol_factory.py:34-44``).  This variant makes
channel faults a first-class experimental axis, complementing the
LLM-response fault injection in :mod:`bcg_tpu.engine.fault`:

* ``drop_prob`` — each point-to-point message is silently dropped with
  this probability (the receiver simply never sees the proposal).
* ``delay_prob`` / ``max_delay_rounds`` — a surviving message is, with
  ``delay_prob``, delivered 1..``max_delay_rounds`` rounds LATE: the
  receiver sees a stale proposal (the message's ``round`` field keeps the
  round it was decided in, so agents can in principle notice staleness —
  whether the LLM does is the research question).
* Seeded: fault rolls come from a private ``random.Random(seed)``, so a
  lossy run is exactly reproducible; ``seed=None`` draws fresh entropy
  per run, mirroring the game's own unseeded behavior.

Semantics preserved from the reliable channel (all inherited —
only the :meth:`_route` delivery decision is overridden): neighbour-set
validation still raises on invalid sends, duplicate suppression still
applies (the channel "consumes" a dropped message — retrying the
identical message is a no-op, like a lost UDP datagram), inbox ordering
stays (sender_id, timestamp), and per-round sent-message counts include
dropped messages (an interface counter, comparable across channels).

Channel fault counts surface in ``AgentNetwork.get_network_stats()``
(and from there the run's results JSON) via :meth:`get_fault_stats`.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional

from bcg_tpu.comm.a2a_sim import A2AMessage, A2ASimProtocol


class LossySimProtocol(A2ASimProtocol):
    def __init__(
        self,
        num_agents: int,
        topology: Dict[int, List[int]],
        drop_prob: float = 0.0,
        delay_prob: float = 0.0,
        max_delay_rounds: int = 1,
        seed: Optional[int] = 0,
    ):
        if not 0.0 <= drop_prob <= 1.0:
            raise ValueError(f"drop_prob={drop_prob}: expected [0, 1]")
        if not 0.0 <= delay_prob <= 1.0:
            raise ValueError(f"delay_prob={delay_prob}: expected [0, 1]")
        if max_delay_rounds < 1:
            raise ValueError(
                f"max_delay_rounds={max_delay_rounds}: expected >= 1"
            )
        super().__init__(num_agents, topology)
        self.drop_prob = drop_prob
        self.delay_prob = delay_prob
        self.max_delay_rounds = max_delay_rounds
        self._seed = seed
        self._rng = random.Random(seed)
        self.dropped_count = 0
        self.delayed_count = 0
        # Dropped messages never join an inbox, so the parent's per-round
        # GC would never release their delivered-set entries — track them
        # by send round for clear_round_buffer.
        self._dropped_by_round: Dict[int, List[A2AMessage]] = {}

    def _route(self, receiver_id: int, message: A2AMessage) -> None:
        if self._rng.random() < self.drop_prob:
            self.dropped_count += 1
            self._dropped_by_round.setdefault(message.round, []).append(message)
            return
        delivery_round = message.round
        if self.delay_prob and self._rng.random() < self.delay_prob:
            delivery_round += self._rng.randint(1, self.max_delay_rounds)
            self.delayed_count += 1
        self.message_buffer.setdefault(delivery_round, {}).setdefault(
            receiver_id, []
        ).append(message)

    def clear_round_buffer(self, round: int) -> None:
        super().clear_round_buffer(round)
        for msg in self._dropped_by_round.pop(round, []):
            self.delivered.discard(msg)

    def get_fault_stats(self) -> Dict[str, int]:
        return {"dropped": self.dropped_count, "delayed": self.delayed_count}

    def reset(self) -> None:
        super().reset()
        self._rng = random.Random(self._seed)
        self.dropped_count = 0
        self.delayed_count = 0
        self._dropped_by_round.clear()

    # ------------------------------------------------------ checkpointing

    def snapshot(self) -> Dict:
        """Extends the base channel snapshot with the fault-RNG stream
        position, counters, and dropped-message GC bookkeeping, so a
        resumed lossy run replays the EXACT fault sequence an
        uninterrupted seeded run would have seen (in-flight delayed
        messages ride in the base message_buffer snapshot)."""
        blob = super().snapshot()
        version, state, gauss = self._rng.getstate()
        blob["lossy"] = {
            "rng_state": [version, list(state), gauss],
            "dropped_count": self.dropped_count,
            "delayed_count": self.delayed_count,
            "dropped_by_round": {
                str(r): [m.to_dict() for m in msgs]
                for r, msgs in self._dropped_by_round.items()
            },
        }
        return blob

    def restore(self, blob: Dict) -> None:
        super().restore(blob)
        lossy = blob.get("lossy")
        if lossy is None:  # checkpoint from a reliable-channel run
            return
        version, state, gauss = lossy["rng_state"]
        self._rng.setstate((version, tuple(state), gauss))
        self.dropped_count = lossy["dropped_count"]
        self.delayed_count = lossy["delayed_count"]
        self._dropped_by_round = {
            int(r): [A2AMessage.from_dict(d) for d in msgs]
            for r, msgs in lossy["dropped_by_round"].items()
        }
        # Dropped messages hold delivered-set entries too (dedup).
        for msgs in self._dropped_by_round.values():
            self.delivered.update(msgs)
