"""int8 weight quantization (W8A8) for the bandwidth-bound decode path.

Autoregressive decode reads every weight byte once per token, so on TPU it
is HBM-bandwidth-bound; storing the dense weights as int8 with per-output-
channel absmax scales halves that traffic, and the MXU multiplies int8 at
twice the bf16 rate.  Activations are quantized dynamically per token
(per-row absmax) right before each matmul, the matmul runs int8 x int8 ->
int32 on the MXU, and the result is rescaled in f32 — the standard
"dynamic W8A8" serving recipe.

This replaces the role of vLLM's quantization support in the reference's
engine layer (``quantization`` knob in `EngineConfig`; the reference
passes its engine config straight to vLLM, vllm_agent.py:100-157).
Enable with ``EngineConfig(quantization="int8")`` / ``--quantization int8``.

Scope: the seven dense matmuls per block plus the LM head.  Embedding
lookups stay bf16 (gathers, not matmuls); for tied-embedding models a
separate quantized head copy is materialized so the [D, V] projection —
the single largest weight in small-vocab-heavy models — still benefits.
Norm vectors stay bf16.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Union

import jax
import jax.numpy as jnp

from bcg_tpu.models.configs import ModelSpec

# A quantized dense weight is a dict {"q": int8 [in, out], "scale": f32 [out]}.
QuantizedDense = Dict[str, jax.Array]
DenseWeight = Union[jax.Array, QuantizedDense]

_QUANT_LEAVES = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def _quantize_impl(w: jax.Array) -> QuantizedDense:
    w32 = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=0)
    scale = jnp.maximum(absmax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return {"q": q, "scale": scale}


_quantize_consuming = partial(jax.jit, donate_argnums=0)(_quantize_impl)
_quantize_preserving = jax.jit(_quantize_impl)


def quantize_weight(w, consume: bool = False) -> QuantizedDense:
    """[in, out] bf16/f32 -> int8 + per-output-channel f32 absmax scale.

    Jitted so the op chain fuses: run eagerly it materializes a full f32
    copy of the weight (2x bf16) — quantizing an 8B model's [D, V] head
    that way OOMs a 16 GB chip during INIT.  ``consume=True``
    additionally donates the source buffer (peak = int8 output only) —
    pass it ONLY for a tensor the caller owns exclusively; the default
    preserves the input, matching ``quantize_params(consume=False)``'s
    contract that the bf16 tree stays usable.
    """
    fn = _quantize_consuming if consume else _quantize_preserving
    return fn(jnp.asarray(w))


def is_quantized(w: DenseWeight) -> bool:
    return isinstance(w, dict)


def dense(x: jax.Array, w: DenseWeight, out_dtype=None) -> jax.Array:
    """``x @ w`` where ``w`` is bf16 or a quantized dict.

    Quantized path: per-token (last-axis) dynamic absmax activation quant,
    int8 x int8 -> int32 dot on the MXU, f32 rescale cast to ``out_dtype``
    (default ``x.dtype``; pass f32 on the logits path to keep the full
    accumulator precision instead of bouncing through bf16).
    """
    if out_dtype is None:
        out_dtype = x.dtype
    if not is_quantized(w):
        return (x @ w).astype(out_dtype)
    x32 = x.astype(jnp.float32)
    a_absmax = jnp.max(jnp.abs(x32), axis=-1, keepdims=True)
    a_scale = jnp.maximum(a_absmax, 1e-12) / 127.0
    xq = jnp.clip(jnp.round(x32 / a_scale), -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(
        xq, w["q"],
        dimension_numbers=(((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return (acc.astype(jnp.float32) * a_scale * w["scale"]).astype(out_dtype)


def quantize_params(params: Dict, spec: ModelSpec, consume: bool = False) -> Dict:
    """Quantize every dense matmul weight of a transformer param pytree.

    Returns a new pytree with each of ``_QUANT_LEAVES`` (per layer) and the
    LM head replaced by ``{"q", "scale"}`` dicts.  Tied-embedding models
    gain an explicit quantized ``lm_head`` (from ``embed.T``) so the logits
    projection is quantized while the bf16 embedding table remains for
    token gathers; ``transformer._logits`` prefers ``lm_head`` when
    present, keeping the tie semantically intact.

    ``consume=True`` drops each bf16 source leaf from ``params`` as it is
    quantized, so peak device memory is the int8 model plus ONE bf16
    weight instead of both full copies — the difference between a 14B
    int8 model fitting a single v5e chip or not.  Only pass it for a tree
    the caller owns exclusively.
    """
    out = dict(params)
    out_layers = []
    for layer in params["layers"]:
        new_layer = {}
        for k in list(layer):
            v = layer[k]
            if k in _QUANT_LEAVES:
                new_layer[k] = quantize_weight(v, consume=consume)
                if consume:
                    del layer[k]
                del v  # drop the local bf16 reference immediately
            else:
                new_layer[k] = v
        out_layers.append(new_layer)
    out["layers"] = out_layers
    if "lm_head" in params:
        out["lm_head"] = quantize_weight(params["lm_head"], consume=consume)
        if consume:
            del params["lm_head"]
    elif spec.tie_embeddings:
        out["lm_head"] = quantize_weight(params["embed"].T, consume=True)
    return out


def quantize_leaf_transform(spec: ModelSpec):
    """Per-leaf hook for the checkpoint loader: quantize each dense weight
    AS IT LOADS, so the bf16 tensor is freed before the next one arrives
    (streamed quantized loading; see loader.load_checkpoint_params)."""

    def transform(logical: str, tensor):
        leaf = logical.split(".")[-1]
        if leaf in _QUANT_LEAVES or leaf == "lm_head":
            return quantize_weight(tensor, consume=True)
        return tensor

    return transform


def ensure_quantized_head(params: Dict, spec: ModelSpec) -> Dict:
    """Give tied-embedding models their explicit quantized LM head when a
    leaf-transform load (which never sees an ``lm_head`` tensor) built the
    rest of the tree."""
    if "lm_head" not in params and spec.tie_embeddings:
        params["lm_head"] = quantize_weight(params["embed"].T, consume=True)
    return params
