"""int8 (W8A8) and int4 (grouped W4A16) weight quantization.

Autoregressive decode reads every weight byte once per token, so on TPU it
is HBM-bandwidth-bound; storing the dense weights as int8 with per-output-
channel absmax scales halves that traffic, and the MXU multiplies int8 at
twice the bf16 rate.  Activations are quantized dynamically per token
(per-row absmax) right before each matmul, the matmul runs int8 x int8 ->
int32 on the MXU, and the result is rescaled in f32 — the standard
"dynamic W8A8" serving recipe.

This replaces the role of vLLM's quantization support in the reference's
engine layer (``quantization`` knob in `EngineConfig`; the reference
passes its engine config straight to vLLM, vllm_agent.py:100-157).
Enable with ``EngineConfig(quantization="int8")`` / ``--quantization int8``.

Scope: the seven dense matmuls per block plus the LM head.  Embedding
lookups stay bf16 (gathers, not matmuls); for tied-embedding models a
separate quantized head copy is materialized so the [D, V] projection —
the single largest weight in small-vocab-heavy models — still benefits.
Norm vectors stay bf16.

int4 (``quantization="int4"``) exists for CAPACITY, not speed: grouped
absmax int4 (group 128 along the contraction dim, two values packed per
byte) halves weight memory again vs int8 — the difference between the
reference's 14B preset (config.py:20-25; "24GB+ VRAM" per its README)
fitting a single 16 GB v5e chip or needing tp>=2.  The matmul runs
W4A16: nibbles are sign-extended and dequantized to bf16 (in VMEM by the
Pallas kernel on TPU, ops/w4_matmul.py; materialized by XLA elsewhere)
and the dot runs on the MXU in bf16.

Packing layout (shared contract with the Pallas kernel): a [in, out]
weight packs row ``i`` of the TOP half (rows [0, in/2)) into the low
nibble and row ``i + in/2`` into the high nibble of byte ``[i, out]`` —
contraction is a sum over rows, so splitting ``x`` into matching column
halves needs no nibble interleave on the unpack path.  Group scales are
``[in/group, out]`` bf16; ``in/2`` must divide by the group size so no
group straddles the halves (group shrinks via gcd for tiny test dims).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Dict, Union

import jax
import jax.numpy as jnp

from bcg_tpu.models.configs import ModelSpec

# A quantized dense weight is a dict:
#   int8: {"q": int8 [in, out], "scale": f32 [out]}
#   int4: {"q4": int8 [in//2, out] (two nibbles/byte), "gscale": bf16 [in//group, out]}
QuantizedDense = Dict[str, jax.Array]
DenseWeight = Union[jax.Array, QuantizedDense]

_QUANT_LEAVES = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")

INT4_GROUP = 128


def _quantize_impl(w: jax.Array) -> QuantizedDense:
    w32 = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=0)
    scale = jnp.maximum(absmax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return {"q": q, "scale": scale}


_quantize_consuming = partial(jax.jit, donate_argnums=0)(_quantize_impl)
_quantize_preserving = jax.jit(_quantize_impl)


def quantize_weight(w, consume: bool = False) -> QuantizedDense:
    """[in, out] bf16/f32 -> int8 + per-output-channel f32 absmax scale.

    Jitted so the op chain fuses: run eagerly it materializes a full f32
    copy of the weight (2x bf16) — quantizing an 8B model's [D, V] head
    that way OOMs a 16 GB chip during INIT.  ``consume=True``
    additionally donates the source buffer (peak = int8 output only) —
    pass it ONLY for a tensor the caller owns exclusively; the default
    preserves the input, matching ``quantize_params(consume=False)``'s
    contract that the bf16 tree stays usable.
    """
    fn = _quantize_consuming if consume else _quantize_preserving
    return fn(jnp.asarray(w))


def int4_group_for(in_dim: int, group: int = INT4_GROUP) -> int:
    """Effective group size for a weight's contraction dim:
    ``gcd(in_dim // 2, group)`` — a divisor of the packed half, shrunk
    from the requested group when it cannot divide (tiny test models
    have in-dims like 64; non-power-of-two dims shrink further than the
    largest-divisor-below-group would)."""
    if in_dim % 2:
        raise ValueError(f"int4 packing needs an even in-dim, got {in_dim}")
    return math.gcd(in_dim // 2, group)


def _quantize4_impl(w: jax.Array, group: int) -> QuantizedDense:
    w32 = w.astype(jnp.float32)
    in_dim, out_dim = w32.shape
    grouped = w32.reshape(in_dim // group, group, out_dim)
    absmax = jnp.max(jnp.abs(grouped), axis=1)
    scale = jnp.maximum(absmax, 1e-12) / 7.0                  # [in/group, out]
    # Quantize against the bf16-ROUNDED scale (what dequant will read),
    # so the half-step error bound holds exactly.
    scale = scale.astype(jnp.bfloat16).astype(jnp.float32)
    q = jnp.clip(jnp.round(grouped / scale[:, None, :]), -8, 7)
    q = q.astype(jnp.int8).reshape(in_dim, out_dim)
    half = in_dim // 2
    packed = jnp.bitwise_or(
        jnp.bitwise_and(q[:half], jnp.int8(0x0F)),
        jnp.left_shift(q[half:], 4),
    ).astype(jnp.int8)
    return {"q4": packed, "gscale": scale.astype(jnp.bfloat16)}


_quantize4_consuming = partial(jax.jit, static_argnums=1, donate_argnums=0)(_quantize4_impl)
_quantize4_preserving = partial(jax.jit, static_argnums=1)(_quantize4_impl)


def quantize_weight_int4(w, consume: bool = False, group: int = INT4_GROUP) -> QuantizedDense:
    """[in, out] bf16/f32 -> packed int4 + per-(group, output) bf16 scale.

    Same jit/donate discipline as :func:`quantize_weight` (eager absmax
    would materialize a full f32 copy during a 14B load)."""
    w = jnp.asarray(w)
    g = int4_group_for(w.shape[0], group)
    fn = _quantize4_consuming if consume else _quantize4_preserving
    return fn(w, g)


def unpack_int4(packed: jax.Array) -> jax.Array:
    """Packed [in//2, out] int8 -> [in, out] int8 in [-8, 7].

    Low nibbles are the top half's rows, high nibbles the bottom half's
    (see module docstring); right_shift on int8 is arithmetic, which is
    exactly the sign-extension the low nibble needs after the left
    shift."""
    low = jnp.right_shift(jnp.left_shift(packed, 4), 4)
    high = jnp.right_shift(packed, 4)
    return jnp.concatenate([low, high], axis=0)


def dequantize_int4(w: QuantizedDense) -> jax.Array:
    """Materialize the bf16 weight from an int4 dict (XLA fallback path
    and test oracle; the Pallas kernel does this per-tile in VMEM)."""
    q = unpack_int4(w["q4"]).astype(jnp.float32)              # [in, out]
    gscale = w["gscale"].astype(jnp.float32)                  # [in/g, out]
    group = q.shape[0] // gscale.shape[0]
    scaled = q.reshape(gscale.shape[0], group, -1) * gscale[:, None, :]
    return scaled.reshape(q.shape).astype(jnp.bfloat16)


# ------------------------------------------------------------- int4 KV cache
# Packed-int4 KV entries reuse the int8 cache's axes ([.., Hkv, S, Dh]
# storage with [.., Hkv, S] scales) with the head dim PACKED two values
# per byte and the scales bf16: the nibble split mirrors the weight
# contract above — dims [0, Dh/2) in the low nibble, [Dh/2, Dh) in the
# high nibble of byte [.., d] — so the paged Pallas kernel never
# interleaves nibbles either: it dots each query half against its
# nibble's dequantized half (contraction over Dh splits cleanly).
# Scales are bf16 (not the int8 arm's f32) for the same reason gscale
# is: the scale overhead is what separates a 1.67x capacity win from
# the 2x the packing actually buys at small head dims, and quantizing
# against the bf16-ROUNDED scale keeps the half-step error bound exact.


def kv_int4_layout(head_dim: int):
    """(storage head dim, scale dtype) of the packed-int4 KV layout —
    the ONE definition every allocator (dense slab, paged pool) and the
    engine's boot check derive from, so the packing contract and the
    scale-dtype layout marker cannot drift apart across sites."""
    if head_dim % 2:
        raise ValueError(
            f"int4 KV packing needs an even head dim, got {head_dim}"
        )
    return head_dim // 2, jnp.bfloat16


def quantize_kv_int4(x):
    """bf16/f32 ``[..., Dh]`` -> (packed int8 ``[..., Dh//2]``, bf16
    per-(position, head) absmax scale ``[...]``).  Symmetric absmax
    over the head dim (the LAST axis — packing is last-axis only),
    range [-8, 7]."""
    half, scale_dtype = kv_int4_layout(x.shape[-1])
    x32 = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x32), axis=-1, keepdims=True)
    scale = jnp.where(absmax == 0.0, 1.0, absmax / 7.0)
    # Quantize against the bf16-ROUNDED scale (what dequant will read).
    scale = scale.astype(scale_dtype).astype(jnp.float32)
    q = jnp.clip(jnp.round(x32 / scale), -8, 7).astype(jnp.int8)
    packed = jnp.bitwise_or(
        jnp.bitwise_and(q[..., :half], jnp.int8(0x0F)),
        jnp.left_shift(q[..., half:], 4),
    ).astype(jnp.int8)
    return packed, scale.squeeze(-1).astype(scale_dtype)


def unpack_kv_int4(packed: jax.Array) -> jax.Array:
    """Packed ``[..., Dh//2]`` int8 -> ``[..., Dh]`` int8 in [-8, 7]
    (low nibbles = first half of the head dim; arithmetic right shift
    sign-extends, exactly like :func:`unpack_int4`)."""
    low = jnp.right_shift(jnp.left_shift(packed, 4), 4)
    high = jnp.right_shift(packed, 4)
    return jnp.concatenate([low, high], axis=-1)


def dequantize_kv_int4(packed: jax.Array, scale: jax.Array):
    """Materialize f32 KV from a packed entry slice (XLA fallback path
    and test oracle; the paged Pallas kernel dequantizes per page in
    VMEM without ever forming the unpacked array).  Last-axis only,
    like the quantizer."""
    return unpack_kv_int4(packed).astype(jnp.float32) * jnp.expand_dims(
        scale.astype(jnp.float32), -1
    )


def is_quantized(w: DenseWeight) -> bool:
    return isinstance(w, dict)


def is_int4(w: DenseWeight) -> bool:
    return isinstance(w, dict) and "q4" in w


def _w8a16_prefill_rows() -> int:
    """Row threshold for the experimental W8A16 prefill path (0 = off).

    Read from the environment at TRACE time (first call per shape
    signature), not import time, so tests can monkeypatch it; it is a
    bench A/B knob, not a per-engine config field — if the hardware A/B
    wins it becomes an unconditional shape dispatch like int4's."""
    from bcg_tpu.runtime.envflags import get_int

    return get_int("BCG_TPU_W8A16_PREFILL")


def dense(x: jax.Array, w: DenseWeight, out_dtype=None) -> jax.Array:
    """``x @ w`` where ``w`` is bf16 or a quantized dict.

    Quantized path: per-token (last-axis) dynamic absmax activation quant,
    int8 x int8 -> int32 dot on the MXU, f32 rescale cast to ``out_dtype``
    (default ``x.dtype``; pass f32 on the logits path to keep the full
    accumulator precision instead of bouncing through bf16).
    """
    if out_dtype is None:
        out_dtype = x.dtype
    if not is_quantized(w):
        return (x @ w).astype(out_dtype)
    rows = 1
    for s in x.shape[:-1]:
        rows *= s
    if is_int4(w):
        # W4A16: dequantize to bf16, dot on the MXU.  Path choice is by
        # row count: DECODE shapes (few rows) take the Pallas kernel —
        # one [P, block_f] strip DMA per output tile, weights streamed
        # once as packed int4, dequant in VMEM.  PREFILL shapes (many
        # rows) take the XLA fallback: it materializes the bf16 weight
        # in HBM once per call, which beats the kernel's per-M-block
        # weight re-streaming when the materialization is amortized
        # over thousands of rows (and prefill is compute-bound anyway).
        # Kernel only on a SINGLE device: pallas_call has no SPMD
        # partitioning rule, so under a tp/dp mesh GSPMD would have to
        # replicate (all-gather) the packed weight per call — the XLA
        # fallback partitions normally there.
        # BCG_TPU_DISABLE_W4_KERNEL=1 is the operational kill-switch
        # (read at trace time): if the kernel fails hardware lowering
        # (scripts/probe_w4_kernel.py), large-model serving degrades to
        # the XLA dequant path instead of crashing.
        from bcg_tpu.config import env_flag

        kernel_off = env_flag("BCG_TPU_DISABLE_W4_KERNEL")
        if (rows <= 256 and not kernel_off
                and jax.default_backend() == "tpu" and jax.device_count() == 1):
            from bcg_tpu.ops.w4_matmul import w4a16_matmul

            return w4a16_matmul(x, w["q4"], w["gscale"]).astype(out_dtype)
        return (x.astype(jnp.bfloat16) @ dequantize_int4(w)).astype(out_dtype)
    # EXPERIMENTAL A/B knob (BCG_TPU_W8A16_PREFILL=<row threshold>):
    # at/above the threshold, skip the dynamic activation quantization
    # and run dequantized int8 -> bf16 x bf16 on the MXU instead
    # (W8A16).  Rationale: prefill-shaped matmuls (thousands of rows)
    # measured only ~16% MFU under W8A8 — if the per-row act-quant +
    # f32 rescale chain (VPU-bound elementwise over the full activation)
    # is the tax, W8A16 trades 2x MXU rate for its removal while keeping
    # the int8 weight memory.  0 (default) = off; promote to a plain
    # shape dispatch (like int4's) if hardware A/B wins.
    if 0 < _w8a16_prefill_rows() <= rows:
        w_bf = (w["q"].astype(jnp.float32) * w["scale"]).astype(jnp.bfloat16)
        return (x.astype(jnp.bfloat16) @ w_bf).astype(out_dtype)
    x32 = x.astype(jnp.float32)
    a_absmax = jnp.max(jnp.abs(x32), axis=-1, keepdims=True)
    a_scale = jnp.maximum(a_absmax, 1e-12) / 127.0
    xq = jnp.clip(jnp.round(x32 / a_scale), -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(
        xq, w["q"],
        dimension_numbers=(((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return (acc.astype(jnp.float32) * a_scale * w["scale"]).astype(out_dtype)


def _quantizer(mode: str):
    if mode == "int8":
        return quantize_weight
    if mode == "int4":
        return quantize_weight_int4
    raise ValueError(f"quantization mode {mode!r}: expected 'int8' or 'int4'")


def _sharded_quantizer(mode: str, spec: ModelSpec, mesh):
    """Per-leaf jitted quantizer whose ``out_shardings`` is the leaf's
    ``param_sharding`` (q like the parent weight, scale per-output-
    channel) and whose input is DONATED under ``consume`` — so a
    tp-sharded bf16 leaf quantizes shard-wise with the int8 result laid
    out directly on the mesh, never re-staged replicated.  Jits are
    memoized per (leaf name, shape, consume): layers share shapes, so a
    14B tree compiles each transform once, not once per layer."""
    from bcg_tpu.parallel.sharding import param_sharding

    fns: Dict = {}

    def quantize(logical: str, w, consume: bool):
        leaf = logical.split(".")[-1]
        key = (leaf, w.shape, str(w.dtype), consume)
        fn = fns.get(key)
        if fn is None:
            if mode == "int8":
                impl = _quantize_impl
            else:
                impl = partial(_quantize4_impl, group=int4_group_for(w.shape[0]))
            out_struct = jax.eval_shape(impl, jax.ShapeDtypeStruct(w.shape, w.dtype))
            outs = {
                sub: param_sharding(f"{logical}.{sub}", spec, mesh)
                for sub in out_struct
            }
            fn = jax.jit(
                impl, out_shardings=outs,
                donate_argnums=(0,) if consume else (),
            )
            fns[key] = fn
        # Donation frees the bf16 source shard-wise; it can never ALIAS
        # the int8/int4 output (dtype change), so silence the
        # per-compile "not usable" lowering warning.
        import warnings

        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable"
            )
            return fn(jnp.asarray(w))

    return quantize


def quantize_params(
    params: Dict, spec: ModelSpec, consume: bool = False, mode: str = "int8",
    mesh=None,
) -> Dict:
    """Quantize every dense matmul weight of a transformer param pytree.

    Returns a new pytree with each of ``_QUANT_LEAVES`` (per layer) and the
    LM head replaced by ``{"q", "scale"}`` dicts.  Tied-embedding models
    gain an explicit quantized ``lm_head`` (from ``embed.T``) so the logits
    projection is quantized while the bf16 embedding table remains for
    token gathers; ``transformer._logits`` prefers ``lm_head`` when
    present, keeping the tie semantically intact.

    ``consume=True`` drops each bf16 source leaf from ``params`` as it is
    quantized, so peak device memory is the quantized model plus ONE bf16
    weight instead of both full copies — the difference between a large
    model fitting a single v5e chip or not.  Only pass it for a tree
    the caller owns exclusively.  ``mode`` selects int8 (W8A8) or int4
    (grouped W4A16).

    With ``mesh``, each leaf quantizes through a jitted transform whose
    ``out_shardings`` is the leaf's ``param_sharding``
    (:func:`_sharded_quantizer`): with ``consume`` the per-device peak is
    the quantized model SHARD plus one bf16 leaf shard, not per replica.
    """
    if mesh is not None:
        sharded = _sharded_quantizer(mode, spec, mesh)
        quantize = lambda logical, w, consume: sharded(logical, w, consume)  # noqa: E731
    else:
        plain = _quantizer(mode)
        quantize = lambda logical, w, consume: plain(w, consume=consume)  # noqa: E731
    out = dict(params)
    out_layers = []
    for li, layer in enumerate(params["layers"]):
        new_layer = {}
        for k in list(layer):
            v = layer[k]
            if k in _QUANT_LEAVES:
                new_layer[k] = quantize(f"layers.{li}.{k}", v, consume)
                if consume:
                    del layer[k]
                del v  # drop the local bf16 reference immediately
            else:
                new_layer[k] = v
        out_layers.append(new_layer)
    out["layers"] = out_layers
    if "lm_head" in params:
        out["lm_head"] = quantize("lm_head", params["lm_head"], consume)
        if consume:
            del params["lm_head"]
    elif spec.tie_embeddings:
        out["lm_head"] = quantize("lm_head", params["embed"].T, True)
    return out


def quantize_leaf_transform(spec: ModelSpec, mode: str = "int8"):
    """Per-leaf hook for the checkpoint loader: quantize each dense weight
    AS IT LOADS, so the bf16 tensor is freed before the next one arrives
    (streamed quantized loading; see loader.load_checkpoint_params)."""
    quantize = _quantizer(mode)

    def transform(logical: str, tensor):
        leaf = logical.split(".")[-1]
        if leaf in _QUANT_LEAVES or leaf == "lm_head":
            return quantize(tensor, consume=True)
        return tensor

    return transform


def ensure_quantized_head(
    params: Dict, spec: ModelSpec, mode: str = "int8", mesh=None
) -> Dict:
    """Give tied-embedding models their explicit quantized LM head when a
    leaf-transform load (which never sees an ``lm_head`` tensor) built the
    rest of the tree.  With ``mesh`` the head quantizes under its
    ``param_sharding`` like every other leaf (:func:`_sharded_quantizer`)."""
    if "lm_head" not in params and spec.tie_embeddings:
        if mesh is not None:
            params["lm_head"] = _sharded_quantizer(mode, spec, mesh)(
                "lm_head", params["embed"].T, True
            )
        else:
            params["lm_head"] = _quantizer(mode)(params["embed"].T, consume=True)
    return params
