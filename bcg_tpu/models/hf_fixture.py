"""Hermetic HuggingFace artifact construction.

This environment has zero network egress, so the real-model pipeline —
``find_checkpoint_dir`` → ``load_checkpoint_params`` →
``HFTokenizer.token_bytes`` → token DFA → chat template (everything the
reference gets from the HF hub + vLLM boot, ``vllm_agent.py:100-157``) —
cannot be proven against a downloaded Qwen3 checkpoint.  It CAN be
proven against a *genuine* artifact set constructed on disk:

* a real byte-level-BPE ``tokenizer.json`` built with the ``tokenizers``
  library — GPT-2 byte-unicode alphabet, trained merges, ChatML special
  tokens — loaded through ``transformers.AutoTokenizer`` exactly like a
  hub checkpoint;
* a real-layout safetensors checkpoint: HF parameter names
  (``model.layers.{i}.self_attn.q_proj.weight`` …), ``[out, in]``
  projection layout, bf16 storage, multi-shard with an index file;
* an HF-style ``config.json`` carrying the architecture fields.

Nothing in the loading path knows these artifacts are synthetic — the
only difference from a hub snapshot is that the weights are random.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional

import numpy as np

from bcg_tpu.models.configs import ModelSpec, spec_for_model

# ChatML specials, matching the chat_template fallback family used for
# bcg-hf/* model names (engine/chat_template.py).
CHATML_SPECIALS = ["<|endoftext|>", "<|im_start|>", "<|im_end|>"]
# Llama-3 specials (the header-id template family, reference
# vllm_agent.py:236-252): fixture names containing "llama3" build a
# byte-BPE vocab with these, so the Llama-3 template meets a
# Llama-3-shaped vocabulary (VERDICT round-2 missing #3).
LLAMA3_SPECIALS = [
    "<|begin_of_text|>", "<|end_of_text|>",
    "<|start_header_id|>", "<|end_header_id|>", "<|eot_id|>",
]
# True-SentencePiece specials (Llama-2/Mistral [INST] family,
# vllm_agent.py:254-269): fixture names containing "mistral" build a
# Metaspace-pretokenized vocab — the engine must DETECT it as
# non-byte-level and route token bytes through the metaspace branch.
SP_SPECIALS = ["<unk>", "<s>", "</s>"]


def fixture_family(model_name: str) -> str:
    """Tokenizer/template family for a ``bcg-hf/*`` fixture name —
    intentionally the same name-substring dispatch the chat template
    uses, so fixture artifacts and template selection can't disagree."""
    m = model_name.lower()
    if "llama3" in m or "llama-3" in m:
        return "llama3"
    if "mistral" in m or "llama" in m:
        return "sentencepiece"
    return "chatml"
# A literal-metaspace token added as a NON-special vocab entry: the
# round-1 ``_token_to_bytes`` heuristic (metaspace checked before the
# byte table) silently mis-decoded exactly this shape of entry in a
# byte-level-BPE vocab — kept in the fixture as a permanent regression
# input for the tokenizer tests.
METASPACE_PROBE_TOKEN = "▁probe▁"

# Shard size cap: small enough that the bench-1b fixture splits into
# several shards, exercising the loader's name->file indexing.
_MAX_SHARD_BYTES = 1 << 30


def _training_corpus() -> Iterable[str]:
    """Synthetic corpus shaped like the game's actual token stream:
    prompt prose, agent/value vocabulary, and JSON decision payloads."""
    base = [
        "You are agent_{i} in a multi-agent consensus game. Your current "
        "value is {v}. Propose a value between 0 and 50.",
        '{{"internal_strategy": "converge toward the median of recent '
        'proposals", "value": {v}, "public_reasoning": "Values are '
        'clustering near {v}, so I am moving toward the group."}}',
        '{{"decision": "continue"}} {{"decision": "stop"}} '
        '{{"decision": "abstain"}}',
        "Round {i}: agent_{i} value: {v} | Reasoning: moving toward the "
        "median to reach consensus quickly.",
        "The quick brown fox jumps over the lazy dog. 0123456789 "
        "agreement rate, Byzantine agents may exist, vote to terminate.",
        "history shows values 12, 17, 23, 25, 25, 25 converging; "
        "suspicious outlier at 49 ignored.",
    ]
    for i in range(64):
        for t in base:
            yield t.format(i=i % 10, v=(i * 7) % 51)


def build_tokenizer_files(
    out_dir: str, vocab_size: int, family: str = "chatml"
) -> None:
    """Train and save a tokenizer artifact set into ``out_dir``.

    ``vocab_size`` counts the FULL tokenizer vocabulary (trained entries
    + specials + the metaspace probe token).  ``family``:

    * ``chatml`` — byte-level BPE, ChatML specials (Qwen-style);
    * ``llama3`` — byte-level BPE, Llama-3 header-id specials, eos
      ``<|eot_id|>``;
    * ``sentencepiece`` — Metaspace-pretokenized BPE (true-SentencePiece
      shape: ``▁``-pieces, NOT byte-level), ``<s>``/``</s>`` specials.
    """
    from tokenizers import Tokenizer, decoders, models, pre_tokenizers, trainers

    os.makedirs(out_dir, exist_ok=True)
    if family == "sentencepiece":
        tok = Tokenizer(models.BPE(unk_token="<unk>"))
        tok.pre_tokenizer = pre_tokenizers.Metaspace()
        tok.decoder = decoders.Metaspace()
        trainer = trainers.BpeTrainer(
            vocab_size=vocab_size - len(SP_SPECIALS),
            special_tokens=SP_SPECIALS,
            show_progress=False,
        )
        tok.train_from_iterator(_training_corpus(), trainer)
        tok.save(os.path.join(out_dir, "tokenizer.json"))
        cfg = {
            "tokenizer_class": "PreTrainedTokenizerFast",
            "eos_token": "</s>", "bos_token": "<s>",
            "unk_token": "<unk>", "pad_token": "</s>",
            "model_max_length": 8192,
        }
        specials_map = {"eos_token": "</s>", "bos_token": "<s>",
                        "unk_token": "<unk>", "pad_token": "</s>"}
    else:
        specials = LLAMA3_SPECIALS if family == "llama3" else CHATML_SPECIALS
        n_added = len(specials) + 1
        tok = Tokenizer(models.BPE(unk_token=None))
        tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
        tok.decoder = decoders.ByteLevel()
        trainer = trainers.BpeTrainer(
            vocab_size=vocab_size - n_added,
            special_tokens=[],
            initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
            show_progress=False,
        )
        tok.train_from_iterator(_training_corpus(), trainer)
        tok.add_special_tokens(specials)
        tok.add_tokens([METASPACE_PROBE_TOKEN])
        tok.save(os.path.join(out_dir, "tokenizer.json"))
        if family == "llama3":
            cfg = {
                "tokenizer_class": "PreTrainedTokenizerFast",
                "eos_token": "<|eot_id|>",
                "pad_token": "<|end_of_text|>",
                "bos_token": "<|begin_of_text|>",
                "model_max_length": 8192,
            }
            specials_map = {"eos_token": "<|eot_id|>",
                            "pad_token": "<|end_of_text|>",
                            "bos_token": "<|begin_of_text|>"}
        else:
            cfg = {
                "tokenizer_class": "PreTrainedTokenizerFast",
                "eos_token": "<|im_end|>",
                "pad_token": "<|endoftext|>",
                "bos_token": None,
                "additional_special_tokens": ["<|im_start|>"],
                "model_max_length": 8192,
            }
            specials_map = {"eos_token": "<|im_end|>",
                            "pad_token": "<|endoftext|>"}
    with open(os.path.join(out_dir, "tokenizer_config.json"), "w") as f:
        json.dump(cfg, f, indent=2)
    with open(os.path.join(out_dir, "special_tokens_map.json"), "w") as f:
        json.dump(specials_map, f)


def _hf_config(spec: ModelSpec) -> Dict:
    """HF ``config.json`` payload (architecture family from the name)."""
    family = fixture_family(spec.name)
    arch, mtype = {
        "llama3": (["LlamaForCausalLM"], "llama"),
        "sentencepiece": (["MistralForCausalLM"], "mistral"),
        "chatml": (["Qwen3ForCausalLM"], "qwen3"),
    }[family]
    return {
        "architectures": arch,
        "model_type": mtype,
        "vocab_size": spec.vocab_size,
        "hidden_size": spec.hidden_size,
        "num_hidden_layers": spec.num_layers,
        "num_attention_heads": spec.num_heads,
        "num_key_value_heads": spec.num_kv_heads,
        "head_dim": spec.head_dim,
        "intermediate_size": spec.intermediate_size,
        "rope_theta": spec.rope_theta,
        "rms_norm_eps": spec.rms_eps,
        "max_position_embeddings": spec.max_position,
        "tie_word_embeddings": spec.tie_embeddings,
        "torch_dtype": "bfloat16",
    }


def _tensor_specs(spec: ModelSpec) -> List:
    """(hf_name, shape) for every tensor, HF ``[out, in]`` layout —
    mirror of the loader's ``_LAYER_MAP``/``_TOP_MAP`` so generated
    checkpoints and the loader can never drift apart silently."""
    from bcg_tpu.models.loader import _LAYER_MAP, _TOP_MAP, _TRANSPOSED

    shapes = {
        "embed": (spec.vocab_size, spec.hidden_size),
        "final_norm": (spec.hidden_size,),
        "lm_head": (spec.vocab_size, spec.hidden_size),
        "attn_norm": (spec.hidden_size,),
        "wq": (spec.q_size, spec.hidden_size),
        "wk": (spec.kv_size, spec.hidden_size),
        "wv": (spec.kv_size, spec.hidden_size),
        "bq": (spec.q_size,),
        "bk": (spec.kv_size,),
        "bv": (spec.kv_size,),
        "wo": (spec.hidden_size, spec.q_size),
        "q_norm": (spec.head_dim,),
        "k_norm": (spec.head_dim,),
        "mlp_norm": (spec.hidden_size,),
        "w_gate": (spec.intermediate_size, spec.hidden_size),
        "w_up": (spec.intermediate_size, spec.hidden_size),
        "w_down": (spec.hidden_size, spec.intermediate_size),
    }
    del _TRANSPOSED  # layout already expressed in `shapes`
    out = []
    for logical, hf_name in _TOP_MAP.items():
        if logical == "lm_head" and spec.tie_embeddings:
            continue
        out.append((hf_name, shapes[logical]))
    for i in range(spec.num_layers):
        for logical, template in _LAYER_MAP.items():
            if logical in ("q_norm", "k_norm") and not spec.qk_norm:
                continue
            if logical in ("bq", "bk", "bv") and not spec.attn_bias:
                continue
            out.append((template.format(i=i), shapes[logical]))
    return out


def build_checkpoint(
    model_name: str,
    out_dir: Optional[str] = None,
    seed: int = 0,
    force: bool = False,
) -> str:
    """Materialize the full HF artifact set for ``model_name`` (a
    ``bcg-hf/*`` spec) and return the checkpoint directory.

    Idempotent: an existing complete checkpoint is returned as-is unless
    ``force``.  Weights are N(0, 0.02) bf16 — random, but stored and
    laid out exactly like a hub snapshot.
    """
    import ml_dtypes
    from safetensors.numpy import save_file

    spec = spec_for_model(model_name)
    if spec is None:
        raise ValueError(f"no ModelSpec registered for {model_name!r}")
    if out_dir is None:
        out_dir = os.path.join("checkpoints", model_name.replace("/", "--"))
    done_marker = os.path.join(out_dir, ".complete")
    if os.path.exists(done_marker) and not force:
        return out_dir
    os.makedirs(out_dir, exist_ok=True)

    # Tokenizer vocab leaves headroom below the model vocab, like real
    # families (Qwen3: tokenizer 151669 < embedding 151936).
    build_tokenizer_files(
        out_dir, vocab_size=spec.vocab_size - 64,
        family=fixture_family(model_name),
    )
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump(_hf_config(spec), f, indent=2)

    rng = np.random.default_rng(seed)
    specs = _tensor_specs(spec)
    shards: List[List] = [[]]
    shard_bytes = 0
    for hf_name, shape in specs:
        nbytes = int(np.prod(shape)) * 2
        if shard_bytes and shard_bytes + nbytes > _MAX_SHARD_BYTES:
            shards.append([])
            shard_bytes = 0
        shards[-1].append((hf_name, shape))
        shard_bytes += nbytes

    index = {"metadata": {"total_size": 0}, "weight_map": {}}
    n = len(shards)
    for si, shard in enumerate(shards, start=1):
        fname = (
            "model.safetensors"
            if n == 1
            else f"model-{si:05d}-of-{n:05d}.safetensors"
        )
        tensors = {}
        for hf_name, shape in shard:
            arr = rng.standard_normal(shape, dtype=np.float32) * 0.02
            if hf_name.endswith("norm.weight"):
                arr = np.ones(shape, dtype=np.float32)
            tensors[hf_name] = arr.astype(ml_dtypes.bfloat16)
            index["weight_map"][hf_name] = fname
            index["metadata"]["total_size"] += tensors[hf_name].nbytes
        save_file(tensors, os.path.join(out_dir, fname))
    if n > 1:
        with open(os.path.join(out_dir, "model.safetensors.index.json"), "w") as f:
            json.dump(index, f, indent=2)

    with open(done_marker, "w") as f:
        f.write("ok\n")
    return out_dir
