"""Pre-quantized checkpoint artifacts: quantize once, serve many.

The reference boots its engine from bf16/fp16 HF shards on every run
(``vllm_agent.py:100-157``); a quantized deployment there re-quantizes
at every boot.  This module saves an already-quantized weight tree
(int8 W8A8 or grouped-int4 W4A16, ``models/quantize.py``) to disk as a
safetensors artifact and loads it back directly — boot skips both the
bf16 shard streaming and the quantization pass, and peak memory during
load is the artifact size (int8: ~half the bf16 checkpoint; int4:
~a quarter), which is exactly the capacity margin that lets 8B/14B
models board a 16 GB chip.

Artifact layout (``<dir>/``):

* ``bcg_tpu_quantized.json`` — manifest: format version, quantization
  mode, model/spec fingerprint, and a logical-dtype map (numpy has no
  bf16, so bf16 tensors are stored as their uint16 bit patterns — the
  same convention the HF loader already decodes, ``loader.py:_convert``).
* ``top.safetensors`` — embed / final_norm / lm_head leaves.
* ``layer_NNNN.safetensors`` — one file per decoder layer so a large
  model streams layer-by-layer through host memory in both directions.

Tensors are keyed by logical path ("embed", "layers.3.wq.q", ...) and
stored in the engine's ``[in, out]`` layout — no transpose on load.

Convert a local HF checkpoint from the command line (CPU works)::

    python -m bcg_tpu.models.artifact --model <name-or-dir> \
        --mode int8 --out /path/to/artifact

With a ``mesh``, each leaf is placed under its ``param_sharding`` spec
as it loads (like the HF loader) so tp-sharded large models never
materialize unsharded on one device.
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from bcg_tpu.models.configs import ModelSpec, spec_for_model

MANIFEST = "bcg_tpu_quantized.json"
_FORMAT = "bcg-tpu-quantized-v1"


def _to_numpy(x) -> Tuple[np.ndarray, str]:
    """Device/host array -> (storage ndarray, logical dtype string).

    bf16 is stored as uint16 bit patterns; everything else stores as its
    own numpy dtype.
    """
    arr = np.asarray(jax.device_get(x))
    if arr.dtype == jnp.bfloat16:
        return arr.view(np.uint16), "bfloat16"
    return arr, str(arr.dtype)


def _flatten(prefix: str, leaf, out: Dict[str, np.ndarray], dtypes: Dict[str, str]):
    if isinstance(leaf, dict):  # quantized {"q","scale"} / {"q4","gscale"}
        for k, v in leaf.items():
            arr, dt = _to_numpy(v)
            out[f"{prefix}.{k}"] = arr
            dtypes[f"{prefix}.{k}"] = dt
    else:
        arr, dt = _to_numpy(leaf)
        out[prefix] = arr
        dtypes[prefix] = dt


def save_quantized_artifact(params: Dict, spec: ModelSpec, mode: str, out_dir: str) -> None:
    """Write a quantized (unstacked) param tree as a serve-ready artifact.

    ``params`` must be the post-quantization tree the engine serves
    (``quantize_params`` / streamed ``quantize_leaf_transform`` output,
    including the explicit ``lm_head`` for tied-embedding models).
    Stacked (scan-mode) trees are refused — save before stacking; the
    loading engine re-stacks under its own ``scan_layers`` config.
    """
    if mode not in ("int8", "int4"):
        raise ValueError(f"artifact mode {mode!r}: expected 'int8' or 'int4'")
    if isinstance(params.get("layers"), dict):
        raise ValueError(
            "save_quantized_artifact needs an unstacked tree (list-form "
            "layers); save before stack_layer_params — the loading engine "
            "re-stacks under its own scan_layers config"
        )
    from safetensors.numpy import save_file

    os.makedirs(out_dir, exist_ok=True)
    dtypes: Dict[str, str] = {}

    top: Dict[str, np.ndarray] = {}
    for name in ("embed", "final_norm", "lm_head"):
        if name in params:
            _flatten(name, params[name], top, dtypes)
    save_file(top, os.path.join(out_dir, "top.safetensors"))

    for i, layer in enumerate(params["layers"]):
        flat: Dict[str, np.ndarray] = {}
        for k, v in layer.items():
            _flatten(f"layers.{i}.{k}", v, flat, dtypes)
        save_file(flat, os.path.join(out_dir, f"layer_{i:04d}.safetensors"))

    manifest = {
        "format": _FORMAT,
        "mode": mode,
        "model": spec.name,
        "num_layers": spec.num_layers,
        "hidden_size": spec.hidden_size,
        "vocab_size": spec.vocab_size,
        "num_heads": spec.num_heads,
        "num_kv_heads": spec.num_kv_heads,
        "head_dim": spec.head_dim,
        "intermediate_size": spec.intermediate_size,
        "dtypes": dtypes,
    }
    with open(os.path.join(out_dir, MANIFEST), "w") as f:
        json.dump(manifest, f, indent=1)


def artifact_mode(ckpt_dir: Optional[str]) -> Optional[str]:
    """The quantization mode of the artifact at ``ckpt_dir``, or None if
    the directory is not a pre-quantized artifact (e.g. a plain HF
    checkpoint)."""
    if not ckpt_dir:
        return None
    path = os.path.join(ckpt_dir, MANIFEST)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f).get("mode")


def load_quantized_artifact(
    spec: ModelSpec, ckpt_dir: str, mode: str, mesh=None
) -> Dict:
    """Load a pre-quantized artifact into the engine's param tree.

    Raises ``ValueError`` when the artifact's mode, model name, or any
    model dimension doesn't match what the caller configured — a
    silently mismatched artifact would serve the wrong weights at the
    wrong capacity (and matching num_layers/hidden/vocab alone can hide
    a wrong head or MLP split).

    ``mesh``: place each leaf under its ``param_sharding`` spec AS IT
    LOADS, like the HF loader's ``mesh=`` path — a tp-requiring model
    (e.g. int8 14B on 16 GB chips) must never materialize unsharded on
    one device.
    """
    with open(os.path.join(ckpt_dir, MANIFEST)) as f:
        manifest = json.load(f)
    if manifest.get("format") != _FORMAT:
        raise ValueError(
            f"unknown artifact format {manifest.get('format')!r} in {ckpt_dir}"
        )
    if manifest["mode"] != mode:
        raise ValueError(
            f"artifact at {ckpt_dir} is {manifest['mode']}-quantized but "
            f"config.quantization={mode!r}; re-quantize or match the config"
        )
    if manifest.get("model") != spec.name:
        raise ValueError(
            f"artifact at {ckpt_dir} was saved for model "
            f"{manifest.get('model')!r}, not {spec.name!r}"
        )
    for field in (
        "num_layers", "hidden_size", "vocab_size",
        "num_heads", "num_kv_heads", "head_dim", "intermediate_size",
    ):
        if field in manifest and manifest[field] != getattr(spec, field):
            raise ValueError(
                f"artifact {field}={manifest[field]} does not match "
                f"spec {spec.name!r} ({getattr(spec, field)})"
            )
    from safetensors import safe_open

    sharding_for = None
    if mesh is not None:
        from bcg_tpu.parallel.sharding import param_sharding

        sharding_for = lambda logical: param_sharding(logical, spec, mesh)  # noqa: E731

    dtypes = manifest["dtypes"]

    def restore(name: str, arr: np.ndarray):
        # bf16 bit patterns re-view on the HOST ndarray so the first
        # device placement is already the sharded one — `jnp.asarray`
        # before `device_put` would stage the full tensor unsharded on
        # the default device first (the transient the per-leaf sharded
        # load exists to avoid; same discipline as loader._convert).
        if dtypes.get(name) == "bfloat16":
            import ml_dtypes

            arr = arr.view(np.uint16).view(ml_dtypes.bfloat16)
        if sharding_for is not None:
            return jax.device_put(arr, sharding_for(name))
        return jnp.asarray(arr)

    def read_file(path: str) -> Dict[str, jax.Array]:
        flat: Dict[str, jax.Array] = {}
        with safe_open(path, framework="numpy") as f:
            for name in f.keys():
                flat[name] = restore(name, f.get_tensor(name))
        return flat

    def unflatten(flat: Dict[str, jax.Array], strip: str) -> Dict:
        """Group "wq.q"-style names back into {"wq": {"q": ...}}."""
        out: Dict = {}
        for name, v in flat.items():
            rel = name[len(strip):] if strip and name.startswith(strip) else name
            parts = rel.split(".")
            if len(parts) == 1:
                out[parts[0]] = v
            else:
                out.setdefault(parts[0], {})[parts[1]] = v
        return out

    params: Dict = unflatten(read_file(os.path.join(ckpt_dir, "top.safetensors")), "")
    layers = []
    for i in range(spec.num_layers):
        path = os.path.join(ckpt_dir, f"layer_{i:04d}.safetensors")
        layers.append(unflatten(read_file(path), f"layers.{i}."))
    params["layers"] = layers
    return params


# Non-weight files a serve-ready artifact must carry along (the engine
# boots the tokenizer/template from the same directory, exactly like
# real pre-quantized hub repos ship tokenizer + config beside weights).
_SIDECAR_FILES = (
    "config.json",
    "generation_config.json",
    "tokenizer.json",
    "tokenizer_config.json",
    "special_tokens_map.json",
    "vocab.json",
    "merges.txt",
    "tokenizer.model",
)


def convert_checkpoint(model: str, mode: str, out_dir: str) -> None:
    """HF safetensors checkpoint -> pre-quantized artifact (streamed:
    each weight is quantized as it loads, so the bf16 tree never exists
    whole — the same discipline as engine boot).  Tokenizer and config
    sidecar files are copied so the artifact directory is a complete,
    bootable checkpoint."""
    import shutil

    from bcg_tpu.models.loader import find_checkpoint_dir, load_checkpoint_params
    from bcg_tpu.models.quantize import (
        ensure_quantized_head, quantize_leaf_transform,
    )

    spec = spec_for_model(model)
    src_dir = find_checkpoint_dir(model)
    params = load_checkpoint_params(
        spec, model, leaf_transform=quantize_leaf_transform(spec, mode),
        ckpt_dir=src_dir,
    )
    ensure_quantized_head(params, spec, mode=mode)
    save_quantized_artifact(params, spec, mode, out_dir)
    if src_dir:
        for fname in _SIDECAR_FILES:
            src = os.path.join(src_dir, fname)
            if os.path.exists(src):
                shutil.copy2(src, os.path.join(out_dir, fname))


def main(argv=None) -> None:
    p = argparse.ArgumentParser(
        description="Convert a local HF checkpoint to a pre-quantized "
        "bcg-tpu artifact (quantize once, serve many)"
    )
    p.add_argument("--model", required=True, help="model name or checkpoint dir")
    p.add_argument("--mode", default="int8", choices=["int8", "int4"])
    p.add_argument("--out", required=True, help="artifact output directory")
    args = p.parse_args(argv)
    convert_checkpoint(args.model, args.mode, args.out)
    print(f"saved {args.mode} artifact for {args.model} at {args.out}")


if __name__ == "__main__":
    main()
