"""Decoder-only transformer family.

One configurable architecture (RMSNorm + RoPE + GQA + SwiGLU, the
Qwen3/Llama-3/Mistral shape) covers every model preset the reference
serves through vLLM (config.py:20-25).  Parameters are a plain pytree so
``jax.sharding`` partition specs apply directly.
"""

from bcg_tpu.models.configs import MODEL_SPECS, ModelSpec, spec_for_model
from bcg_tpu.models.transformer import (
    TransformerParams,
    init_params,
    prefill,
    prefill_with_prefix,
    decode_step,
)

__all__ = [
    "ModelSpec",
    "MODEL_SPECS",
    "spec_for_model",
    "TransformerParams",
    "init_params",
    "prefill",
    "prefill_with_prefix",
    "decode_step",
]
