"""Model architecture specs.

Shapes for the reference's model presets (Qwen3-8B/14B/32B,
Mistral-Small-22B — reference config.py:20-25) plus a tiny hermetic spec
for tests and CPU smoke runs.  All are the same architecture family:
pre-RMSNorm decoder blocks, rotary positions, grouped-query attention,
SwiGLU MLP.  Qwen3 additionally applies RMSNorm to per-head q/k
projections (qk_norm=True).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional


# Parameter count at/above which single-chip serving needs the memory
# levers (int8 KV + scan-over-layers): an 8B-class bf16 KV cache next to
# int8 weights exceeds a 16 GB v5e.  Shared by the bench's config gates
# and the engine's int8-KV speed warning.
LARGE_MODEL_PARAMS = 6_000_000_000

# At/above this, even int8 weights (>= 12 GB) crowd out the KV cache on
# a 16 GB chip: single-chip serving needs the int4 weight path
# (models/quantize.py quantize_weight_int4) — the reference's 14B preset
# is the first to cross it.
XL_MODEL_PARAMS = 12_000_000_000


@dataclass(frozen=True)
class RopeScaling:
    """Llama-3.1-style NTK-by-parts rope scaling (HF ``rope_type:
    "llama3"``): frequencies whose wavelength exceeds the original
    training context are stretched by ``factor``, short wavelengths are
    kept, and the band between is smoothly interpolated."""

    factor: float = 8.0
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_position: int = 8192


@dataclass(frozen=True)
class ModelSpec:
    name: str
    vocab_size: int
    hidden_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    intermediate_size: int
    rope_theta: float = 1_000_000.0
    rms_eps: float = 1e-6
    qk_norm: bool = False          # Qwen3-style per-head q/k RMSNorm
    attn_bias: bool = False        # Qwen2-style q/k/v projection biases
    rope_scaling: Optional[RopeScaling] = None
    tie_embeddings: bool = False
    max_position: int = 40960

    @property
    def q_size(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_size(self) -> int:
        return self.num_kv_heads * self.head_dim

    @property
    def matmul_params_per_layer(self) -> int:
        """Dense matmul parameters of one decoder block (q/k/v/o +
        SwiGLU MLP) — the unit both the size-class gate and the bench's
        MFU accounting are built from (single source, so they can't
        drift)."""
        return (
            self.hidden_size * (self.q_size + 2 * self.kv_size)
            + self.q_size * self.hidden_size
            + 3 * self.hidden_size * self.intermediate_size
        )

    @property
    def param_count(self) -> int:
        """Approximate parameter count (matmuls + embeddings; norm
        vectors are noise at this granularity).  Size-class gates key on
        this instead of substring-matching model names — ``"8b" in
        model`` silently mis-defaulted renamed or larger presets
        (VERDICT round-2 weak #6)."""
        embed = self.vocab_size * self.hidden_size
        embed_total = embed if self.tie_embeddings else 2 * embed
        return embed_total + self.num_layers * self.matmul_params_per_layer

    def weight_bytes(self, quantization: Optional[str] = None) -> int:
        """Estimated served-weight footprint in bytes for a quantization
        mode (None = bf16, "int8" = W8A8, "int4" = grouped W4A16).

        Counts what the engine actually holds: the bf16 embedding table
        (token gathers stay bf16), a quantized LM head (explicit for
        tied models too, models/quantize.py), and the per-layer matmul
        weights with their scale tensors (int8: f32 per-output-channel;
        int4: bf16 per (group=128, output)).  Norm vectors are noise.
        This is the capacity-math half of the single-chip fit question;
        add KV cache + activations (config-dependent) for the total.
        """
        embed = self.vocab_size * self.hidden_size  # bf16 gathers
        mm = self.num_layers * self.matmul_params_per_layer + embed  # + head
        # Scale elements = one per output channel (int8) or per
        # (group, output) (int4).  Output-channel totals per layer:
        out_per_layer = (
            self.q_size + 2 * self.kv_size + self.hidden_size
            + 2 * self.intermediate_size + self.hidden_size
        )
        out_total = self.num_layers * out_per_layer + self.vocab_size
        if quantization is None:
            # Tied bf16 serving shares ONE table (transformer._logits
            # uses embed.T; no lm_head is stored) — don't double-count.
            head_bf16 = 0 if self.tie_embeddings else embed
            return embed * 2 + (mm - embed + head_bf16) * 2
        if quantization == "int8":
            return embed * 2 + mm + out_total * 4
        if quantization == "int4":
            group = 128
            # gscale elements ~= (in/group) * out summed over matmuls
            # ~= mm / group.
            return embed * 2 + mm // 2 + (mm // group) * 2
        raise ValueError(f"unknown quantization {quantization!r}")


MODEL_SPECS: Dict[str, ModelSpec] = {
    # Qwen3 dense family (HF config.json values).
    "Qwen/Qwen3-8B": ModelSpec(
        name="Qwen/Qwen3-8B",
        vocab_size=151936, hidden_size=4096, num_layers=36,
        num_heads=32, num_kv_heads=8, head_dim=128,
        intermediate_size=12288, qk_norm=True,
    ),
    "Qwen/Qwen3-14B": ModelSpec(
        name="Qwen/Qwen3-14B",
        vocab_size=151936, hidden_size=5120, num_layers=40,
        num_heads=40, num_kv_heads=8, head_dim=128,
        intermediate_size=17408, qk_norm=True,
    ),
    "Qwen/Qwen3-32B": ModelSpec(
        name="Qwen/Qwen3-32B",
        vocab_size=151936, hidden_size=5120, num_layers=64,
        num_heads=64, num_kv_heads=8, head_dim=128,
        intermediate_size=25600, qk_norm=True,
    ),
    # Families beyond the reference's presets that its engine layer
    # special-cases chat templates for (vllm_agent.py:199-292) — specs
    # here so those templates are servable, not just formattable.
    "Qwen/Qwen2.5-7B-Instruct": ModelSpec(
        name="Qwen/Qwen2.5-7B-Instruct",
        vocab_size=152064, hidden_size=3584, num_layers=28,
        num_heads=28, num_kv_heads=4, head_dim=128,
        intermediate_size=18944, attn_bias=True, max_position=32768,
    ),
    "meta-llama/Meta-Llama-3.1-8B-Instruct": ModelSpec(
        name="meta-llama/Meta-Llama-3.1-8B-Instruct",
        vocab_size=128256, hidden_size=4096, num_layers=32,
        num_heads=32, num_kv_heads=8, head_dim=128,
        intermediate_size=14336, rope_theta=500_000.0,
        rms_eps=1e-5, rope_scaling=RopeScaling(), max_position=131072,
    ),
    "mistralai/Mistral-Small-Instruct-2409": ModelSpec(
        name="mistralai/Mistral-Small-Instruct-2409",
        vocab_size=32768, hidden_size=6144, num_layers=56,
        num_heads=48, num_kv_heads=8, head_dim=128,
        intermediate_size=16384, rope_theta=1_000_000.0,
        rms_eps=1e-5, max_position=32768,
    ),
    # Hermetic HF-artifact specs (models/hf_fixture.py): loaded through
    # the REAL checkpoint pipeline — AutoTokenizer + safetensors shards +
    # config.json on local disk — with random weights.  `tiny` proves the
    # pipeline on CPU in tests; `bench-1b` is the TPU-scale variant.
    "bcg-hf/tiny": ModelSpec(
        name="bcg-hf/tiny",
        vocab_size=512, hidden_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, head_dim=16,
        intermediate_size=128, qk_norm=True, max_position=2048,
    ),
    "bcg-hf/bench-1b": ModelSpec(
        name="bcg-hf/bench-1b",
        vocab_size=32768, hidden_size=2048, num_layers=16,
        num_heads=16, num_kv_heads=8, head_dim=128,
        intermediate_size=6144, qk_norm=True, max_position=8192,
    ),
    # Family-fidelity fixtures (models/hf_fixture.py): Llama-3-shaped
    # byte-BPE vocab (<|eot_id|> specials, header-id template) and a
    # true-SentencePiece Mistral-shaped one ([INST] template, Metaspace
    # pieces) — so template selection and tokenizer detection are proven
    # against each family the reference special-cases
    # (vllm_agent.py:199-292), not just ChatML.
    "bcg-hf/tiny-llama3": ModelSpec(
        name="bcg-hf/tiny-llama3",
        vocab_size=512, hidden_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, head_dim=16,
        intermediate_size=128, rope_theta=500_000.0,
        rms_eps=1e-5, max_position=2048,
    ),
    "bcg-hf/tiny-mistral": ModelSpec(
        name="bcg-hf/tiny-mistral",
        vocab_size=512, hidden_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, head_dim=16,
        intermediate_size=128, rms_eps=1e-5, max_position=2048,
    ),
    # Hermetic tiny model: byte tokenizer vocabulary, runs on CPU in ms.
    "bcg-tpu/tiny-test": ModelSpec(
        name="bcg-tpu/tiny-test",
        vocab_size=512, hidden_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, head_dim=16,
        intermediate_size=128, qk_norm=True, max_position=2048,
    ),
    # Tiny spec with a LANE-ALIGNED head dim (128): exercises the
    # TPU-kernel selection branches (Pallas decode/flash gating keys on
    # head_dim % 128) at test sizes where tiny-test's Dh=16 cannot.
    "bcg-tpu/tiny-dh128": ModelSpec(
        name="bcg-tpu/tiny-dh128",
        vocab_size=512, hidden_size=256, num_layers=2,
        num_heads=2, num_kv_heads=1, head_dim=128,
        intermediate_size=512, qk_norm=True, max_position=2048,
    ),
    # Mid-size random-weight spec for single-chip benchmarking.
    "bcg-tpu/bench-1b": ModelSpec(
        name="bcg-tpu/bench-1b",
        vocab_size=151936, hidden_size=2048, num_layers=16,
        num_heads=16, num_kv_heads=8, head_dim=128,
        intermediate_size=6144, qk_norm=True, max_position=8192,
    ),
    # Qwen3-8B dims with random weights: real-scale single-chip serving
    # (int8 weights ~8.8 GB incl. the bf16 embedding — fits one v5e-16GB
    # chip with the KV cache and a reduced prefix-cache budget).
    "bcg-tpu/bench-8b": ModelSpec(
        name="bcg-tpu/bench-8b",
        vocab_size=151936, hidden_size=4096, num_layers=36,
        num_heads=32, num_kv_heads=8, head_dim=128,
        intermediate_size=12288, qk_norm=True, max_position=8192,
    ),
    # Qwen3-14B / 32B dims with random weights: the reference's larger
    # presets (config.py:20-25) as hermetic multi-chip TP targets —
    # int8 14B (~15 GB) needs tp>=2 on 16 GB chips, 32B tp>=4.  Shard
    # layouts validated on the virtual CPU mesh (tests/test_parallel.py,
    # __graft_entry__.dryrun_multichip).
    "bcg-tpu/bench-14b": ModelSpec(
        name="bcg-tpu/bench-14b",
        vocab_size=151936, hidden_size=5120, num_layers=40,
        num_heads=40, num_kv_heads=8, head_dim=128,
        intermediate_size=17408, qk_norm=True, max_position=8192,
    ),
    "bcg-tpu/bench-32b": ModelSpec(
        name="bcg-tpu/bench-32b",
        vocab_size=151936, hidden_size=5120, num_layers=64,
        num_heads=64, num_kv_heads=8, head_dim=128,
        intermediate_size=25600, qk_norm=True, max_position=8192,
    ),
}


def spec_for_model(model_name: str) -> Optional[ModelSpec]:
    return MODEL_SPECS.get(model_name)
