"""Functional decoder-only transformer (RMSNorm / RoPE / GQA / SwiGLU).

TPU-first design choices:

* Parameters are a plain dict pytree; :mod:`bcg_tpu.parallel.sharding`
  assigns ``NamedSharding`` per leaf (heads and the MLP intermediate dim
  partition over the ``tp`` mesh axis — Megatron layout: column-parallel
  in-projections, row-parallel out-projections).
* Static shapes everywhere: prefill is [B, L] with an explicit validity
  mask (left-padded batches), decode is a [B, 1] step against a
  preallocated KV cache updated via ``dynamic_update_slice``.
* Weights and KV cache are bf16; RMSNorm accumulates in f32; attention
  logits/softmax run in f32 for stability.
* The attention inner op is pluggable (``attention_impl``): the stock
  XLA path (einsum softmax einsum — XLA fuses it well on MXU) or the
  Pallas flash kernel in :mod:`bcg_tpu.ops.attention`.

Replaces the CUDA side of the reference's engine (vLLM internals behind
``vllm_agent.py:100-157``); no reference code exists at this layer.
"""

from __future__ import annotations

import math
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from bcg_tpu.models.configs import ModelSpec
from bcg_tpu.models.quantize import dense

TransformerParams = Dict  # pytree: see init_params for the layout


# ----------------------------------------------------------------- building

def param_plan(spec: ModelSpec):
    """Ordered ``(logical_name, init_kind, shape)`` triples for every
    leaf :func:`init_params` creates — ``init_kind`` is ``"dense"``
    (random, scaled by 1/sqrt(fan_in)), ``"ones"`` (norm vectors) or
    ``"zeros"`` (projection biases).

    This is the single source of truth for the parameter layout: the
    eager initializer (:func:`init_params`), the born-sharded
    initializer (``models/loader.py::init_random_params_sharded``) and
    the analytic boot-memory accounting (``loader.boot_peak_report``)
    all iterate it, so creation order, key consumption and shapes
    cannot drift between the materializing and the abstract paths.

    Key-consumption contract: dense leaves consume one key each, in
    plan order, from ``jax.random.split(key, 4 + num_layers * 7)``.
    """
    plan = [
        ("embed", "dense", (spec.vocab_size, spec.hidden_size)),
        ("final_norm", "ones", (spec.hidden_size,)),
    ]
    for li in range(spec.num_layers):
        pre = f"layers.{li}."
        plan += [
            (pre + "attn_norm", "ones", (spec.hidden_size,)),
            (pre + "wq", "dense", (spec.hidden_size, spec.q_size)),
            (pre + "wk", "dense", (spec.hidden_size, spec.kv_size)),
            (pre + "wv", "dense", (spec.hidden_size, spec.kv_size)),
            (pre + "wo", "dense", (spec.q_size, spec.hidden_size)),
            (pre + "mlp_norm", "ones", (spec.hidden_size,)),
            (pre + "w_gate", "dense", (spec.hidden_size, spec.intermediate_size)),
            (pre + "w_up", "dense", (spec.hidden_size, spec.intermediate_size)),
            (pre + "w_down", "dense", (spec.intermediate_size, spec.hidden_size)),
        ]
        if spec.qk_norm:
            plan += [
                (pre + "q_norm", "ones", (spec.head_dim,)),
                (pre + "k_norm", "ones", (spec.head_dim,)),
            ]
        if spec.attn_bias:
            plan += [
                (pre + "bq", "zeros", (spec.q_size,)),
                (pre + "bk", "zeros", (spec.kv_size,)),
                (pre + "bv", "zeros", (spec.kv_size,)),
            ]
    if not spec.tie_embeddings:
        plan.append(("lm_head", "dense", (spec.hidden_size, spec.vocab_size)))
    return plan


def assemble_param_tree(items) -> TransformerParams:
    """``(logical_name, leaf)`` pairs -> the nested param pytree
    (``layers.{i}.{name}`` paths become ``params["layers"][i][name]``)."""
    params: Dict = {}
    for logical, leaf in items:
        parts = logical.split(".")
        if parts[0] == "layers":
            layers = params.setdefault("layers", [])
            li = int(parts[1])
            while len(layers) <= li:
                layers.append({})
            layers[li][parts[2]] = leaf
        else:
            params[logical] = leaf
    return params


def init_params(
    spec: ModelSpec, key: jax.Array, dtype=jnp.bfloat16, leaf_transform=None
) -> TransformerParams:
    """Random-init parameters with the HF-compatible logical layout.

    Layout (per layer l):
      embed            [V, D]
      layers.l.attn_norm [D]
      layers.l.wq      [D, H*Dh]    layers.l.wk/wv [D, Hkv*Dh]
      layers.l.wo      [H*Dh, D]
      layers.l.q_norm/k_norm [Dh]   (qk_norm models only)
      layers.l.bq/bk/bv             (attn_bias models only, e.g. Qwen2)
      layers.l.mlp_norm [D]
      layers.l.w_gate/w_up [D, F]   layers.l.w_down [F, D]
      final_norm       [D]
      lm_head          [D, V]       (absent when tie_embeddings)

    ``leaf_transform(logical_name, tensor)`` (same hook as the streamed
    checkpoint loader) is applied to each dense weight AS IT IS CREATED,
    so e.g. int8 quantization never holds the whole bf16 model: an
    8B-class random-weight bench would otherwise OOM a 16 GB chip during
    init alone.

    This EAGER path still creates every leaf replicated on the default
    device with an fp32 intermediate per tensor — for flagship-scale
    specs use ``models/loader.py::init_random_params_sharded``, which
    materializes each leaf of the same :func:`param_plan` (same shapes,
    same key consumption) through a jitted per-leaf initializer under
    its ``param_sharding``, so no leaf ever exists unsharded.  Its
    VALUES intentionally differ bit-wise from this path's (it scopes the
    partitionable RNG for mesh-shape invariance); random weights carry
    no golden-value contract.
    """
    keys = iter(jax.random.split(key, 4 + spec.num_layers * 7))

    def build(logical, kind, shape):
        if kind == "dense":
            w = (
                jax.random.normal(next(keys), shape, jnp.float32)
                / math.sqrt(shape[0])
            ).astype(dtype)
            return leaf_transform(logical, w) if leaf_transform else w
        return (jnp.ones if kind == "ones" else jnp.zeros)(shape, dtype)

    return assemble_param_tree(
        (logical, build(logical, kind, shape))
        for logical, kind, shape in param_plan(spec)
    )


def stack_layer_params(
    params: TransformerParams, consume: bool = False, mesh=None, spec=None
) -> TransformerParams:
    """Convert ``params["layers"]`` from a per-layer list to a STACKED
    pytree (each leaf gains a leading ``[num_layers]`` dim) for
    scan-over-layers execution.

    Why: every per-layer Python iteration unrolls into the HLO, so an
    unrolled 36-layer 8B program is ~36x the module size of its scanned
    equivalent — large enough that this environment's remote-compile
    helper rejects it (BENCH_NOTES round 1: HTTP 500 on 8B-sized
    programs).  ``lax.scan`` over stacked weights emits the block ONCE.

    Stacks leaf-group by leaf-group; with ``consume`` each group's
    per-layer source buffers are dropped as soon as its stack exists, so
    peak device memory is the model plus ONE leaf-group instead of two
    full copies — stacking an 8B int8 model non-consuming OOMs a 16 GB
    chip (measured).  Only pass ``consume`` for a tree the caller owns.

    With ``mesh`` (and ``spec``), each leaf-group stacks through a
    jitted transform whose ``out_shardings`` is the group's stacked
    ``param_sharding`` and whose inputs are DONATED under ``consume`` —
    so a tp/dp-sharded tree stays sharded through the stack and the
    leaf-group transient is per device SHARD, not per replica (a 14B
    tree stacking replicated would re-stage dp×/tp× the bytes the
    born-sharded init just avoided).
    """
    layers = params["layers"]
    if isinstance(layers, dict):
        return params

    stack_group = None
    if mesh is not None:
        if spec is None:
            raise ValueError("stack_layer_params(mesh=...) needs spec= too")
        from bcg_tpu.parallel.sharding import param_sharding

        def _stack(ls):
            if isinstance(ls[0], dict):
                return {k: jnp.stack([lv[k] for lv in ls]) for k in ls[0]}
            return jnp.stack(ls)

        # Memoized per (leaf signature, output shardings): same-shaped
        # groups — wk/wv, w_gate/w_up, the norm vectors — share ONE
        # compiled stack instead of re-lowering identical programs
        # (compiles sit on the boot path this function exists to slim).
        stack_fns: Dict = {}

        def stack_group(name, leaves):
            sample = leaves[0]
            if isinstance(sample, dict):
                outs = {
                    k: param_sharding(
                        f"layers.{name}.{k}", spec, mesh, stacked=True
                    )
                    for k in sample
                }
                sig = tuple(
                    sorted(
                        (k, v.shape, str(v.dtype), outs[k].spec)
                        for k, v in sample.items()
                    )
                )
            else:
                outs = param_sharding(f"layers.{name}", spec, mesh, stacked=True)
                sig = (sample.shape, str(sample.dtype), outs.spec)
            key = (sig, len(leaves))
            fn = stack_fns.get(key)
            if fn is None:
                fn = jax.jit(
                    _stack, out_shardings=outs,
                    donate_argnums=(0,) if consume else (),
                )
                stack_fns[key] = fn
            # Donation here frees each per-layer source as its slice is
            # copied; it can never ALIAS the stacked output (leading dim
            # added), so silence the per-compile "not usable" lowering
            # warning — the free, not the alias, is the point.
            import warnings

            with warnings.catch_warnings():
                warnings.filterwarnings(
                    "ignore", message="Some donated buffers were not usable"
                )
                return fn(leaves)

    out = dict(params)
    stacked: Dict = {}
    for name in list(layers[0].keys()):
        if consume:
            leaves = [l.pop(name) for l in layers]
        else:
            leaves = [l[name] for l in layers]
        if stack_group is not None:
            stacked[name] = stack_group(name, leaves)
        elif isinstance(leaves[0], dict):  # quantized {"q", "scale"}
            stacked[name] = {
                k: jnp.stack([lv[k] for lv in leaves]) for k in leaves[0]
            }
        else:
            stacked[name] = jnp.stack(leaves)
        del leaves
    out["layers"] = stacked
    return out


def layers_stacked(params: TransformerParams) -> bool:
    return isinstance(params["layers"], dict)


# ------------------------------------------------------------------ kernels

def rms_norm(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    x32 = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * scale).astype(x.dtype) * weight


def rope_table(
    positions: jax.Array, head_dim: int, theta: float, scaling=None
) -> Tuple[jax.Array, jax.Array]:
    """cos/sin tables for the given positions ([..., P] -> [..., P, Dh/2]).

    ``scaling`` is an optional :class:`~bcg_tpu.models.configs.RopeScaling`
    (Llama-3.1 "llama3" NTK-by-parts): long-wavelength frequencies divide
    by ``factor``, short ones are kept, the band between interpolates.
    """
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    if scaling is not None:
        wavelen = 2.0 * math.pi / inv_freq
        low_wl = scaling.original_max_position / scaling.low_freq_factor
        high_wl = scaling.original_max_position / scaling.high_freq_factor
        smooth = (
            scaling.original_max_position / wavelen - scaling.low_freq_factor
        ) / (scaling.high_freq_factor - scaling.low_freq_factor)
        scaled = jnp.where(
            wavelen > low_wl,
            inv_freq / scaling.factor,
            jnp.where(
                wavelen < high_wl,
                inv_freq,
                (1 - smooth) * inv_freq / scaling.factor + smooth * inv_freq,
            ),
        )
        inv_freq = scaled
    angles = positions.astype(jnp.float32)[..., None] * inv_freq
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotate half (HF convention). x: [B, T, H, Dh]; cos/sin: [B, T, Dh/2]."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    cos = cos[:, :, None, :]
    sin = sin[:, :, None, :]
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _xla_attention(q, k, v, mask, scale):
    """Stock attention: einsum -> masked f32 softmax -> einsum.

    q: [B, T, H, Dh], k/v: [B, S, Hkv, Dh], mask: [B, T, S] bool.
    """
    B, T, H, Dh = q.shape
    Hkv = k.shape[2]
    group = H // Hkv
    qg = q.reshape(B, T, Hkv, group, Dh)
    logits = jnp.einsum("bthgd,bshd->bhgts", qg, k).astype(jnp.float32) * scale
    logits = jnp.where(mask[:, None, None, :, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    out = jnp.einsum("bhgts,bshd->bthgd", probs, v)
    return out.reshape(B, T, H, Dh)


def attention(q, k, v, mask, scale, impl: str = "xla"):
    if impl == "pallas":
        from bcg_tpu.ops.attention import flash_attention

        return flash_attention(q, k, v, mask, scale)
    if impl == "blockwise":
        from bcg_tpu.ops.attention import blockwise_attention

        return blockwise_attention(q, k, v, mask, scale)
    return _xla_attention(q, k, v, mask, scale)


# ------------------------------------------------------------------ forward

def kv_is_int4(entry: Dict) -> bool:
    """True for a packed-int4 KV entry.  The marker is the SCALE dtype —
    int4 scales are bf16 where the int8 arm's are f32 (see
    quantize.quantize_kv_int4) — so every layout this repo stores KV in
    (dense slab, paged pool, gathered dense view, per-entry prefix KV)
    carries its own dtype without the caller needing the model head dim
    to disambiguate the packed storage shape."""
    return "k_scale" in entry and entry["k_scale"].dtype == jnp.bfloat16


def _kv_quantizer(entry: Dict):
    """The fresh-KV quantizer a quantized entry needs, so every write
    path (dense scalar, dense per-row, paged) shares one dispatch that
    cannot drift from the allocation.  Both quantizers share the
    ``[B, T, Hkv, Dh] -> (storage values, [B, T, Hkv] scales)``
    signature; only the storage head dim (packed Dh/2 vs Dh) differs."""
    if kv_is_int4(entry):
        from bcg_tpu.models.quantize import quantize_kv_int4

        return quantize_kv_int4
    from bcg_tpu.ops.decode_attention import quantize_kv

    return quantize_kv


def _kv_dequantizer(entry: Dict):
    """The matching ``(values, scale) -> f32`` dequantizer (the XLA
    fallback / gather paths; kernels dequantize in VMEM)."""
    if kv_is_int4(entry):
        from bcg_tpu.models.quantize import dequantize_kv_int4

        return dequantize_kv_int4
    from bcg_tpu.ops.decode_attention import dequantize_kv

    return dequantize_kv


def _write_cache(entry: Dict, k, v, pos) -> Dict:
    """Write fresh k/v into the cache entry (quantizing if it is int8
    or packed int4 — the entry's scale dtype selects, see
    :func:`_kv_quantizer`).

    ``pos`` is either a scalar (one shared cache slot for the whole
    batch — prefill chunks, the standard/fast-forward decode loops) or a
    [B] vector of PER-ROW slots (the speculative decode loop, whose rows
    advance by their own accepted-token counts and keep the cache fully
    compacted — no masked gaps streamed by later steps).

    Quantized entries store k/v [B, Hkv, S, Dh] (S-major-of-last-two):
    int8 arrays tile as (32, 128) on the last two dims, so a kernel block
    slicing S x Dh is native — the bf16 layout's [.., S, Hkv, Dh] would
    hand Mosaic (1, 128)-row int8 blocks (measured ~70x slower decode).

    A PAGED entry (block pool + per-row block table, ``"tbl"`` present —
    :mod:`bcg_tpu.ops.paged_attention`) routes both position forms
    through the block-indexed scatter instead; the logical semantics
    are identical.
    """
    if "tbl" in entry:
        from bcg_tpu.ops.paged_attention import paged_write

        return paged_write(entry, k, v, pos)
    if getattr(pos, "ndim", 0) == 1:
        return _write_cache_rows(entry, k, v, pos)
    new = dict(entry)
    if "k_scale" in entry:
        quantize_kv = _kv_quantizer(entry)
        kq, ksc = quantize_kv(k)   # kq: [B, T, Hkv, Dh(/2)]; ksc: [B, T, Hkv]
        vq, vsc = quantize_kv(v)
        new["k"] = jax.lax.dynamic_update_slice(
            entry["k"], kq.transpose(0, 2, 1, 3), (0, 0, pos, 0))
        new["v"] = jax.lax.dynamic_update_slice(
            entry["v"], vq.transpose(0, 2, 1, 3), (0, 0, pos, 0))
        new["k_scale"] = jax.lax.dynamic_update_slice(
            entry["k_scale"], ksc.transpose(0, 2, 1), (0, 0, pos))
        new["v_scale"] = jax.lax.dynamic_update_slice(
            entry["v_scale"], vsc.transpose(0, 2, 1), (0, 0, pos))
    else:
        new["k"] = jax.lax.dynamic_update_slice(entry["k"], k.astype(entry["k"].dtype), (0, pos, 0, 0))
        new["v"] = jax.lax.dynamic_update_slice(entry["v"], v.astype(entry["v"].dtype), (0, pos, 0, 0))
    return new


def _write_cache_rows(entry: Dict, k, v, row_pos) -> Dict:
    """Per-row-position variant of :func:`_write_cache`: row ``b``'s
    [T]-token chunk lands at cache slots ``[row_pos[b], row_pos[b]+T)``
    (a scatter instead of ``dynamic_update_slice``; indices are in
    bounds by the caller's slot provisioning)."""
    new = dict(entry)
    B, T = k.shape[0], k.shape[1]
    bidx = jnp.arange(B)[:, None]                       # [B, 1]
    sidx = row_pos[:, None] + jnp.arange(T)[None, :]    # [B, T]
    if "k_scale" in entry:
        quantize_kv = _kv_quantizer(entry)
        kq, ksc = quantize_kv(k)   # kq: [B, T, Hkv, Dh(/2)]; ksc: [B, T, Hkv]
        vq, vsc = quantize_kv(v)
        # Storage [B, Hkv, S, Dh] / scales [B, Hkv, S]: advanced indices
        # on axes (0, 2) move to the front, so the target region is
        # [B, T, Hkv, Dh] / [B, T, Hkv] — already the fresh-KV layout.
        new["k"] = entry["k"].at[bidx, :, sidx].set(kq)
        new["v"] = entry["v"].at[bidx, :, sidx].set(vq)
        new["k_scale"] = entry["k_scale"].at[bidx, :, sidx].set(ksc)
        new["v_scale"] = entry["v_scale"].at[bidx, :, sidx].set(vsc)
    else:
        new["k"] = entry["k"].at[bidx, sidx].set(k.astype(entry["k"].dtype))
        new["v"] = entry["v"].at[bidx, sidx].set(v.astype(entry["v"].dtype))
    return new


def _cache_attention(q, entry: Dict, mask, scale, impl: str):
    """Decode-step attention over the (possibly int8) cache.

    q: [B, 1, H, Dh]; mask: [B, S] attendable slots.  The Pallas decode
    kernel streams the cache once and dequantizes in VMEM; off-TPU (or
    non-lane-aligned head dims) falls back to dequantize + stock einsum.
    """
    if "tbl" in entry:
        # Paged cache (ops/paged_attention.py): ``impl`` carries the
        # engine-resolved paged marker — "paged_pallas"(+"_it") runs
        # the fused page-gather kernel, anything else the block-table
        # gather + stock masked attention (bit-identical to the dense
        # path given identical block contents).
        from bcg_tpu.ops.paged_attention import paged_decode_attention

        return paged_decode_attention(q, entry, mask, scale, impl=impl)
    quantized = "k_scale" in entry
    Dh = q.shape[-1]
    # The dense Pallas decode kernel streams int8 storage only — the
    # packed-int4 slab takes the dequant fallback (the engine never
    # resolves "pallas" for an int4 dense cache; belt and suspenders).
    if impl == "pallas" and jax.default_backend() == "tpu" \
            and Dh % 128 == 0 and not kv_is_int4(entry):
        from bcg_tpu.ops.decode_attention import decode_attention

        return decode_attention(
            q[:, 0], entry["k"], entry["v"], mask, scale,
            k_scale=entry.get("k_scale"), v_scale=entry.get("v_scale"),
        )[:, None]
    k, v = entry["k"], entry["v"]
    if quantized:
        dequantize_kv = _kv_dequantizer(entry)

        # Quantized cache layout is [B, Hkv, S, Dh(/2 packed)] with
        # scales [B, Hkv, S]; the (slow-path) full dequant transposes
        # back to the attention layout [B, S, Hkv, Dh].
        k = dequantize_kv(k, entry["k_scale"]).transpose(0, 2, 1, 3).astype(q.dtype)
        v = dequantize_kv(v, entry["v_scale"]).transpose(0, 2, 1, 3).astype(q.dtype)
    return _xla_attention(q, k, v, mask[:, None, :], scale)


def _cache_len(cache) -> int:
    """Allocated cache length S, across layouts: bf16 k is
    [(Lyr,) B, S, Hkv, Dh]; quantized storage is [(Lyr,) B, Hkv, S, Dh]."""
    entry = cache if isinstance(cache, dict) else cache[0]
    return entry["k"].shape[-2 if "k_scale" in entry else -3]


def _dequant_slice(entry: Dict, name: str, upto: int, dtype) -> jax.Array:
    """Cache slots [0, upto) of k or v as [B, upto, Hkv, Dh], dequantized
    (and transposed out of the [B, Hkv, S, Dh] storage) if stored int8.
    Paged entries gather only the table's first ``upto / bs`` block
    columns (the caller block-aligns the prefix region) to the same
    dense view first."""
    if "tbl" in entry:
        from bcg_tpu.ops.paged_attention import block_size, paged_gather_entry

        bs = block_size(entry)
        assert upto % bs == 0, (
            f"paged history window {upto} not block-aligned (bs={bs})"
        )
        entry = paged_gather_entry(entry, upto_blocks=upto // bs)
    scale_name = f"{name}_scale"
    if scale_name not in entry:
        return entry[name][:, :upto].astype(dtype)
    dequantize_kv = _kv_dequantizer(entry)

    # astype BEFORE the transpose: the transpose is the materialization
    # point, and a bf16 buffer halves its traffic vs transposing in f32.
    return dequantize_kv(
        entry[name][:, :, :upto], entry[scale_name][:, :, :upto]
    ).astype(dtype).transpose(0, 2, 1, 3)


def _block(
    layer: Dict,
    spec: ModelSpec,
    x: jax.Array,              # [B, T, D]
    cos: jax.Array,
    sin: jax.Array,
    kv_write_pos: jax.Array,   # scalar: where in the cache to write
    cache_entry: Dict,         # {k, v[, k_scale, v_scale]}, [B, S, ...]
    attn_mask: jax.Array,      # prefill: [B, T, hist_len+T] over hist+chunk;
                               # decode (T == 1): [B, S] over the cache
    impl: str,
    hist_len: int = 0,         # static: cache slots [0, hist_len) hold a
                               # reusable prefix (prefix caching)
    ring=None,                 # static (Mesh, axis_name): sequence-parallel
                               # ring attention for the full-prefill branch
    kv_valid=None,             # [B, T] bool, ring mode only (pads False)
) -> Tuple[jax.Array, Dict]:
    B, T, D = x.shape
    h = rms_norm(x, layer["attn_norm"], spec.rms_eps)
    q, k, v = dense(h, layer["wq"]), dense(h, layer["wk"]), dense(h, layer["wv"])
    if "bq" in layer:  # Qwen2-style projection biases
        q, k, v = q + layer["bq"], k + layer["bk"], v + layer["bv"]
    q = q.reshape(B, T, spec.num_heads, spec.head_dim)
    k = k.reshape(B, T, spec.num_kv_heads, spec.head_dim)
    v = v.reshape(B, T, spec.num_kv_heads, spec.head_dim)
    if spec.qk_norm:
        q = rms_norm(q, layer["q_norm"], spec.rms_eps)
        k = rms_norm(k, layer["k_norm"], spec.rms_eps)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    new_entry = _write_cache(cache_entry, k, v, kv_write_pos)

    scale = 1.0 / math.sqrt(spec.head_dim)
    if T > 1 and ring is not None:
        # Sequence-parallel full prefill: K/V blocks rotate over the sp
        # ring (ops/ring_attention.py) instead of materializing the
        # [B, T, T] mask and scores on one device.  Causality is by
        # physical position (left-padding preserves order) and pads are
        # masked via kv_valid — exactly prefill()'s mask semantics.
        from bcg_tpu.ops.ring_attention import ring_attention

        assert hist_len == 0, "ring prefill has no cached-prefix path"
        mesh, axis_name = ring
        attn_out = ring_attention(
            q, k, v, mesh, axis_name=axis_name, causal=True, scale=scale,
            kv_valid=kv_valid,
        )
    elif T > 1 and hist_len > 0:
        # Suffix prefill: the chunk attends over the cached prefix KV
        # plus itself.  Prefix slots are read once per call instead of
        # being recomputed — the point of prefix caching.
        hk = _dequant_slice(cache_entry, "k", hist_len, q.dtype)
        hv = _dequant_slice(cache_entry, "v", hist_len, q.dtype)
        attn_out = attention(
            q, jnp.concatenate([hk, k], axis=1),
            jnp.concatenate([hv, v], axis=1), attn_mask, scale, impl,
        )
    elif T > 1:
        # Prefill attends over the FRESH bf16 chunk (nothing earlier is
        # in the cache), so prefill cost is O(L^2) not O(L*S_cache) and
        # is unaffected by cache quantization.
        attn_out = attention(q, k, v, attn_mask, scale, impl)
    elif ring is not None:
        # Sequence-parallel decode: the cache stays sharded over sp and
        # each device attends its slice; partials merge via pmax/psum of
        # O(B*H) stats (ops/ring_attention.sp_decode_attention).  An
        # int8 cache dequantizes only its local S/sp slice inside the
        # shard_map.  Indivisible cache length is a LOUD error, not a
        # silent fallback: the engine aligns its cache allocation to sp
        # (jax_engine._kv_align), so reaching here with S % sp != 0
        # means that guarantee broke — and a silent replicated fallback
        # once made this whole path dead while its feature flag read as
        # active.
        from bcg_tpu.ops.ring_attention import sp_decode_attention

        assert not kv_is_int4(new_entry), (
            "int4 KV does not compose with sp-sharded decode (the ring "
            "kernels dequantize int8 scales) — the engine rejects the "
            "pairing at boot"
        )
        mesh, axis_name = ring
        attn_out = sp_decode_attention(
            q[:, 0], new_entry["k"], new_entry["v"], attn_mask, mesh,
            axis_name=axis_name, scale=scale,
            k_scale=new_entry.get("k_scale"),
            v_scale=new_entry.get("v_scale"),
        )[:, None]
    else:
        attn_out = _cache_attention(q, new_entry, attn_mask, scale, impl)
    x = x + dense(attn_out.reshape(B, T, spec.q_size), layer["wo"])

    h = rms_norm(x, layer["mlp_norm"], spec.rms_eps)
    gate = jax.nn.silu(dense(h, layer["w_gate"]))
    x = x + dense(gate * dense(h, layer["w_up"]), layer["w_down"])
    return x, new_entry


def _run_layers(
    params: TransformerParams,
    spec: ModelSpec,
    x: jax.Array,
    cos, sin,
    write_pos: jax.Array,
    cache,
    attn_mask: jax.Array,
    impl: str,
    hist_len: int = 0,
    chunk: bool = False,
    ring=None,
    kv_valid=None,
):
    """Apply every decoder block: a Python loop for list-form params
    (each layer unrolled into the HLO — best when the program already
    compiles), or ONE ``lax.scan`` over stacked params + stacked cache
    (program size O(1) in depth — the 8B-unblocking path; see
    ``stack_layer_params``).  The scanned cache rides the scan CARRY
    (dynamic_index/dynamic_update per layer) — riding xs/ys would
    materialize a second full cache, which OOMs at 8B — and keeps the
    same [Lyr, ...] layout."""
    layers = params["layers"]
    if isinstance(layers, dict):
        # The cache rides the scan CARRY, not xs/ys: ys would be a second
        # full-cache allocation (XLA could not alias the donated input
        # through scan — measured OOM at 8B where cache ~6.8 GB), while
        # carry buffers update in place inside the underlying while loop.
        num_layers = jax.tree.leaves(cache)[0].shape[0]

        def body(carry, per_layer):
            h, c = carry
            li, lp = per_layer
            ce = jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(a, li, 0, keepdims=False),
                c,
            )
            if chunk:
                h, entry = _block_chunk(
                    lp, spec, h, cos, sin, write_pos, ce, attn_mask, impl,
                    ring=ring,
                )
            else:
                h, entry = _block(
                    lp, spec, h, cos, sin, write_pos, ce, attn_mask, impl,
                    hist_len=hist_len, ring=ring, kv_valid=kv_valid,
                )
            c = jax.tree.map(
                lambda a, e: jax.lax.dynamic_update_index_in_dim(a, e, li, 0),
                c, entry,
            )
            return (h, c), None

        (x, new_cache), _ = jax.lax.scan(
            body, (x, cache), (jnp.arange(num_layers), layers)
        )
        return x, new_cache
    new_cache = []
    for li, layer in enumerate(layers):
        if chunk:
            x, entry = _block_chunk(
                layer, spec, x, cos, sin, write_pos, cache[li], attn_mask,
                impl, ring=ring,
            )
        else:
            x, entry = _block(
                layer, spec, x, cos, sin, write_pos, cache[li], attn_mask,
                impl, hist_len=hist_len, ring=ring, kv_valid=kv_valid,
            )
        new_cache.append(entry)
    return x, new_cache


def _logits(params: TransformerParams, spec: ModelSpec, x: jax.Array) -> jax.Array:
    h = rms_norm(x, params["final_norm"], spec.rms_eps)
    # Quantized tied-embedding models carry an explicit quantized lm_head
    # (see quantize.quantize_params), so prefer it when present; an untied
    # model without one is a loader bug that must stay loud.
    if "lm_head" in params:
        return dense(h, params["lm_head"], out_dtype=jnp.float32)
    if not spec.tie_embeddings:
        raise KeyError(f"params for untied model {spec.name!r} lack 'lm_head'")
    return (h @ params["embed"].T).astype(jnp.float32)


def init_kv_cache(
    spec: ModelSpec, batch: int, max_len: int, dtype=jnp.bfloat16,
    quantized=False, stacked: bool = False,
):
    """Per-layer list of {k, v[, k_scale, v_scale]} leaves, or — with
    ``stacked`` — ONE dict whose leaves carry a leading [num_layers] dim
    (the scan-over-layers cache; must match ``stack_layer_params``).

    k/v are [B, S, Hkv, Dh]; with ``quantized`` (True or ``"int8"``)
    they are int8 stored [B, Hkv, S, Dh] — int8 tiles as (32, 128) over
    the last two dims, so an S x Dh kernel block is Mosaic-native (the
    bf16 axis order would hand it (1, 128)-row int8 blocks) — with f32
    per-(position, kv-head) absmax scales stored [B, Hkv, S] (S minor,
    lane-aligned).  Halves the HBM traffic of the bandwidth-bound decode
    step; the kernels dequantize in VMEM (see ops/decode_attention.py).

    ``quantized="int4"`` packs the head dim two values per byte on the
    same axes ([B, Hkv, S, Dh/2] storage) with BF16 scales — the scale
    dtype is the layout marker (:func:`kv_is_int4`) — halving KV bytes
    again vs int8: the capacity knob that roughly doubles admissible
    batch at a fixed HBM budget (see models/quantize.py's int4-KV
    contract).

    The list form keeps separate pytree leaves so the
    ``dynamic_update_slice`` in each decode step is a pure per-buffer
    update XLA can alias in-place inside ``lax.while_loop``.  The stacked
    form trades some of that aliasing freedom (scan's ys re-stack the
    entries) for an O(1)-in-depth program — the 8B compile unblocking."""
    if quantized == "int4":
        from bcg_tpu.models.quantize import kv_int4_layout

        dh_store, scale_dtype = kv_int4_layout(spec.head_dim)
    else:
        dh_store, scale_dtype = spec.head_dim, jnp.float32
    shape = (batch, max_len, spec.num_kv_heads, spec.head_dim)
    qshape = (batch, spec.num_kv_heads, max_len, dh_store)
    scale_shape = (batch, spec.num_kv_heads, max_len)

    def entry(lead=()):
        if quantized:
            return {
                "k": jnp.zeros(lead + qshape, jnp.int8),
                "v": jnp.zeros(lead + qshape, jnp.int8),
                "k_scale": jnp.ones(lead + scale_shape, scale_dtype),
                "v_scale": jnp.ones(lead + scale_shape, scale_dtype),
            }
        return {
            "k": jnp.zeros(lead + shape, dtype),
            "v": jnp.zeros(lead + shape, dtype),
        }

    if stacked:
        return entry(lead=(spec.num_layers,))
    return [entry() for _ in range(spec.num_layers)]


def prefill(
    params: TransformerParams,
    spec: ModelSpec,
    tokens: jax.Array,        # [B, L] left-padded
    valid: jax.Array,         # [B, L] bool, False on pads
    cache: Dict,              # from init_kv_cache, written at [0, L)
    impl: str = "xla",
) -> Tuple[jax.Array, Dict]:
    """Process the full prompt; returns last-position logits and the cache.

    Left-padding: positions count only valid tokens, so RoPE sees each
    sequence starting at 0; pads are masked out of attention entirely.
    """
    B, L = tokens.shape
    positions = jnp.cumsum(valid.astype(jnp.int32), axis=1) - 1
    positions = jnp.maximum(positions, 0)
    cos, sin = rope_table(positions, spec.head_dim, spec.rope_theta, spec.rope_scaling)

    causal = jnp.tril(jnp.ones((L, L), bool))
    # Prefill attends over the fresh [B, L] chunk only — nothing beyond L
    # is in the cache yet, so no padded-cache slots are ever touched.
    attn_mask = causal[None] & valid[:, None, :] & valid[:, :, None]  # [B, L, L]

    x = params["embed"][tokens]
    x, new_cache = _run_layers(
        params, spec, x, cos, sin, jnp.int32(0), cache, attn_mask, impl
    )
    logits = _logits(params, spec, x[:, -1:, :])[:, 0, :]  # [B, V]
    return logits, new_cache


def prefill_sp(
    params: TransformerParams,
    spec: ModelSpec,
    tokens: jax.Array,        # [B, L] left-padded, L divisible by sp
    valid: jax.Array,         # [B, L] bool, False on pads
    cache: Dict,
    mesh,                     # jax.sharding.Mesh with an `axis_name` axis
    axis_name: str = "sp",
    impl: str = "xla",
) -> Tuple[jax.Array, Dict]:
    """Sequence-parallel full-prompt prefill: ring attention over ``sp``.

    Long-context serving (SURVEY.md §5.7 stretch goal made first-class):
    the token dimension is sharded over the ``sp`` mesh axis, so per-chip
    prefill activation memory is O(L/sp) and attention never materializes
    the [B, L, L] score matrix on one device — K/V blocks rotate around
    the ICI ring (ops/ring_attention.py).  Per-token work (norms, matmuls,
    RoPE) partitions over the same axis via the sharding constraint; XLA
    SPMD inserts the collectives.  Results match :func:`prefill` (same
    causal-by-physical-position + validity mask semantics; left-padding
    preserves order).  The reference has no long-context machinery at
    all — it compresses context instead (truncation ladders,
    bcg_agents.py:632, a2a_sim.py:69-73).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    B, L = tokens.shape
    sp = mesh.shape[axis_name]
    if L % sp:
        raise ValueError(f"prompt length {L} not divisible by sp={sp}")
    positions = jnp.cumsum(valid.astype(jnp.int32), axis=1) - 1
    positions = jnp.maximum(positions, 0)
    cos, sin = rope_table(positions, spec.head_dim, spec.rope_theta,
                          spec.rope_scaling)

    x = params["embed"][tokens]
    x = jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(None, axis_name, None))
    )
    x, new_cache = _run_layers(
        params, spec, x, cos, sin, jnp.int32(0), cache, None, impl,
        ring=(mesh, axis_name), kv_valid=valid,
    )
    logits = _logits(params, spec, x[:, -1:, :])[:, 0, :]
    return logits, new_cache


def prefill_with_prefix(
    params: TransformerParams,
    spec: ModelSpec,
    tokens: jax.Array,         # [B, Ls] left-padded suffix tokens
    valid: jax.Array,          # [B, Ls] bool, False on pads
    cache: Dict,               # slots [0, P) already hold prefix KV
    prefix_valid: jax.Array,   # [B, P] attendable prefix slots
    prefix_lens: jax.Array,    # [B] valid prefix token counts (RoPE offset)
    impl: str = "xla",
) -> Tuple[jax.Array, Dict]:
    """Prefill the per-call suffix against a cached prompt prefix.

    Prefix caching: the static system-prompt segment is prefilled once per
    run (slots [0, P) of the cache) and only the round-specific suffix is
    processed here, with RoPE positions continuing where each row's prefix
    ended.  The suffix chunk KV is written at slots [P, P+Ls).
    """
    B, Ls = tokens.shape
    P = prefix_valid.shape[1]
    positions = prefix_lens[:, None] + jnp.cumsum(valid.astype(jnp.int32), axis=1) - 1
    positions = jnp.maximum(positions, 0)
    cos, sin = rope_table(positions, spec.head_dim, spec.rope_theta, spec.rope_scaling)

    causal = jnp.tril(jnp.ones((Ls, Ls), bool))
    chunk_mask = causal[None] & valid[:, None, :] & valid[:, :, None]   # [B, Ls, Ls]
    hist_mask = prefix_valid[:, None, :] & valid[:, :, None]            # [B, Ls, P]
    attn_mask = jnp.concatenate([hist_mask, chunk_mask], axis=2)        # [B, Ls, P+Ls]

    x = params["embed"][tokens]
    x, new_cache = _run_layers(
        params, spec, x, cos, sin, jnp.int32(P), cache, attn_mask, impl,
        hist_len=P,
    )
    logits = _logits(params, spec, x[:, -1:, :])[:, 0, :]
    return logits, new_cache


def prefill_paged(
    params: TransformerParams,
    spec: ModelSpec,
    tokens: jax.Array,         # [B, Ls] RIGHT-padded (left-aligned) tokens
    valid: jax.Array,          # [B, Ls] bool, False on trailing pads
    cache: Dict,               # paged entries; logical slots [0, P) hold
                               # radix-shared prefix blocks
    prefix_valid: jax.Array,   # [B, P] attendable prefix slots (P may be 0)
    prefix_lens: jax.Array,    # [B] valid prefix token counts (RoPE offset)
    impl: str = "xla",
) -> Tuple[jax.Array, Dict]:
    """Prefill into a PAGED cache: the per-call chunk (full prompt when
    ``P == 0``, or the suffix past the radix-resident prefix) is written
    at logical slots ``[P, P+Ls)`` through each row's block table.

    Differs from :func:`prefill_with_prefix` in exactly two ways, both
    forced by block paging: tokens arrive LEFT-aligned (so full
    real-token blocks are radix-insertable — a left-pad would interleave
    pad KV into shareable blocks), and logits are taken at each row's
    last VALID position instead of the last physical one (with trailing
    pads those differ).  Attention math is unchanged: causality is by
    physical position, pads are masked, RoPE counts only valid tokens.
    """
    B, Ls = tokens.shape
    P = prefix_valid.shape[1]
    positions = prefix_lens[:, None] + jnp.cumsum(valid.astype(jnp.int32), axis=1) - 1
    positions = jnp.maximum(positions, 0)
    cos, sin = rope_table(positions, spec.head_dim, spec.rope_theta, spec.rope_scaling)

    causal = jnp.tril(jnp.ones((Ls, Ls), bool))
    chunk_mask = causal[None] & valid[:, None, :] & valid[:, :, None]   # [B, Ls, Ls]
    hist_mask = prefix_valid[:, None, :] & valid[:, :, None]            # [B, Ls, P]
    attn_mask = jnp.concatenate([hist_mask, chunk_mask], axis=2)        # [B, Ls, P+Ls]

    x = params["embed"][tokens]
    x, new_cache = _run_layers(
        params, spec, x, cos, sin, jnp.int32(P), cache, attn_mask, impl,
        hist_len=P,
    )
    last = jnp.sum(valid.astype(jnp.int32), axis=1) - 1                 # [B]
    last = jnp.maximum(last, 0)
    h_last = jnp.take_along_axis(x, last[:, None, None], axis=1)        # [B, 1, D]
    logits = _logits(params, spec, h_last)[:, 0, :]
    return logits, new_cache


def prefill_paged_chunk_at(
    params: TransformerParams,
    spec: ModelSpec,
    tokens: jax.Array,         # [B, C] one RIGHT-padded prefill chunk
    valid: jax.Array,          # [B, C] bool, False on trailing pads
    cache: Dict,               # paged entries; slots [0, H) may hold
                               # prior context (prefix + earlier chunks)
    hist_valid: jax.Array,     # [B, H] attendable prior slots (False at
                               # and past the chunk's own write region)
    pos_offset: jax.Array,     # [B] RoPE position of each row's first
                               # valid chunk token
    write_pos: jax.Array,      # scalar int32: cache slot of chunk col 0
    carry_logits: jax.Array,   # [B, V] f32: last-valid logits so far
    impl: str = "xla",
) -> Tuple[jax.Array, Dict]:
    """One chunk of a PAGED chunked prefill — :func:`prefill_chunk_at`'s
    block-pool sibling: the history window is a fixed ``[B, H]`` mask
    and the write slot a traced scalar, so every full-width chunk of
    every offset shares one compiled program per ``(B, C, H)``, with
    two paged differences.  Chunks arrive RIGHT-padded (left-aligned,
    the radix-insertable orientation — see :func:`prefill_paged`), so a
    row's last valid token may sit mid-chunk; and because rows END in
    different chunks, the final logits thread through ``carry_logits``:
    each call takes logits at the row's last valid position *within
    this chunk* and keeps the carry for rows with no valid tokens here.
    Right-padding makes valid tokens contiguous from column 0, so after
    the final chunk the carry holds every row's true last-valid logits.
    The chunk's KV lands at logical slots ``[write_pos, write_pos+C)``
    through each row's block table; ``H`` must be block-aligned (the
    history gather reads whole table columns — the engine aligns its
    chunk size to the pool's block size)."""
    B, C = tokens.shape
    positions = pos_offset[:, None] + jnp.cumsum(valid.astype(jnp.int32), axis=1) - 1
    positions = jnp.maximum(positions, 0)
    cos, sin = rope_table(positions, spec.head_dim, spec.rope_theta, spec.rope_scaling)

    H = hist_valid.shape[1]
    causal = jnp.tril(jnp.ones((C, C), bool))
    chunk_mask = causal[None] & valid[:, None, :] & valid[:, :, None]   # [B, C, C]
    hist_mask = hist_valid[:, None, :] & valid[:, :, None]              # [B, C, H]
    attn_mask = jnp.concatenate([hist_mask, chunk_mask], axis=2)        # [B, C, H+C]

    x = params["embed"][tokens]
    x, new_cache = _run_layers(
        params, spec, x, cos, sin, write_pos, cache, attn_mask, impl,
        hist_len=H,
    )
    nvalid = jnp.sum(valid.astype(jnp.int32), axis=1)                   # [B]
    last = jnp.maximum(nvalid - 1, 0)
    h_last = jnp.take_along_axis(x, last[:, None, None], axis=1)        # [B, 1, D]
    logits = _logits(params, spec, h_last)[:, 0, :]
    logits = jnp.where((nvalid > 0)[:, None], logits, carry_logits)
    return logits, new_cache


def prefill_chunk_at(
    params: TransformerParams,
    spec: ModelSpec,
    tokens: jax.Array,         # [B, C] one prefill chunk, left-aligned pads ok
    valid: jax.Array,          # [B, C] bool
    cache: Dict,               # slots [0, H) may hold prior context
    hist_valid: jax.Array,     # [B, H] attendable prior slots (False past
                               # the chunk's own write region)
    pos_offset: jax.Array,     # [B] RoPE position of each row's first
                               # valid chunk token
    write_pos: jax.Array,      # scalar int32: cache slot of chunk col 0
    impl: str = "xla",
    ring=None,                 # static (Mesh, axis_name): sp-sharded-cache
                               # chunked prefill (sp_chunk_decode_attention)
) -> Tuple[jax.Array, Dict]:
    """One chunk of a chunked prefill with a DYNAMIC write position.

    Unlike :func:`prefill_with_prefix` (whose history width — and hence
    compiled shape — grows with every chunk offset), the history window
    here is a fixed ``[B, H]`` mask and the chunk's cache slot arrives as
    a traced scalar, so EVERY chunk of every offset shares one compiled
    program per (B, C, H).  On a remote-compile environment that turns
    an 8B boot's L/C prefill compiles into one.

    With ``ring`` the chunk instead attends the WHOLE sp-sharded cache
    (its own slots written first) through the decode loops' chunk path —
    this matters most for the LARGE size class, whose default config is
    exactly chunked prefill, so an 8B+ long-context sp deployment would
    otherwise never engage sequence parallelism at prefill.
    """
    B, C = tokens.shape
    positions = pos_offset[:, None] + jnp.cumsum(valid.astype(jnp.int32), axis=1) - 1
    positions = jnp.maximum(positions, 0)
    cos, sin = rope_table(positions, spec.head_dim, spec.rope_theta, spec.rope_scaling)

    H = hist_valid.shape[1]
    causal = jnp.tril(jnp.ones((C, C), bool))
    chunk_mask = causal[None] & valid[:, None, :] & valid[:, :, None]   # [B, C, C]
    hist_mask = hist_valid[:, None, :] & valid[:, :, None]              # [B, C, H]

    x = params["embed"][tokens]
    if ring is not None:
        # [B, C, S] whole-cache mask: history slots in [0, H) (hist_valid
        # is already False at and past the chunk's write region), the
        # chunk's own causally-visible slots at [write_pos, write_pos+C).
        # _block_chunk writes the chunk KV before attending, so the key
        # set matches the hist-concat form exactly; only the (sharded)
        # storage it reads from differs.
        S = _cache_len(cache)
        full_mask = jnp.zeros((B, C, S), bool)
        full_mask = full_mask.at[:, :, :H].set(hist_mask)
        full_mask = jax.lax.dynamic_update_slice(
            full_mask, chunk_mask, (0, 0, write_pos)
        )
        x, new_cache = _run_layers(
            params, spec, x, cos, sin, write_pos, cache, full_mask, impl,
            chunk=True, ring=ring,
        )
    else:
        attn_mask = jnp.concatenate([hist_mask, chunk_mask], axis=2)
        x, new_cache = _run_layers(
            params, spec, x, cos, sin, write_pos, cache, attn_mask, impl,
            hist_len=H,
        )
    logits = _logits(params, spec, x[:, -1:, :])[:, 0, :]
    return logits, new_cache


def decode_step(
    params: TransformerParams,
    spec: ModelSpec,
    token: jax.Array,          # [B] current tokens
    write_pos: jax.Array,      # scalar int32: cache slot to write
    seq_positions: jax.Array,  # [B] RoPE positions of these tokens
    cache: Dict,
    valid_mask: jax.Array,     # [B, S] which cache slots are attendable
    impl: str = "xla",
    ring=None,                 # static (Mesh, axis_name): sp-sharded-cache
                               # decode (ops/ring_attention.sp_decode_attention)
) -> Tuple[jax.Array, Dict]:
    """One autoregressive step for the whole batch."""
    B = token.shape[0]
    cos, sin = rope_table(seq_positions[:, None], spec.head_dim, spec.rope_theta, spec.rope_scaling)
    x = params["embed"][token][:, None, :]  # [B, 1, D]

    x, new_cache = _run_layers(
        params, spec, x, cos, sin, write_pos, cache, valid_mask, impl,
        ring=ring,
    )
    logits = _logits(params, spec, x)[:, 0, :]
    return logits, new_cache


def decode_chunk(
    params: TransformerParams,
    spec: ModelSpec,
    tokens: jax.Array,         # [B, K] chunk: sampled token + forced chain
    chunk_valid: jax.Array,    # [B, K] bool; position 0 always valid
    write_pos: jax.Array,      # scalar int32: cache slot of chunk position 0
    positions: jax.Array,      # [B, K] RoPE positions (per-row real counts)
    cache: Dict,
    cache_valid: jax.Array,    # [B, S] attendable cache slots BEFORE chunk
    impl: str = "xla",
    ring=None,                 # static (Mesh, axis_name): sp-sharded-cache
                               # chunk decode (sp_chunk_decode_attention)
) -> Tuple[jax.Array, Dict]:
    """One fast-forward step: process a [B, K] token chunk against the
    cache (forced-chain fast-forward — the sampled token plus up to K-1
    DFA-forced JSON-skeleton tokens per row in a single weight pass).

    The chunk is written at cache slots [write_pos, write_pos+K); rows
    whose chain is shorter leave trailing slots invalid (gaps — masked
    from all later attention by ``cache_valid``).  Returns logits at each
    row's LAST VALID chunk position and the updated cache.
    """
    B, K = tokens.shape
    cos, sin = rope_table(positions, spec.head_dim, spec.rope_theta, spec.rope_scaling)

    # Mask: chunk queries attend to valid prior cache slots plus the
    # causally-visible valid part of the chunk itself.
    S = cache_valid.shape[1]
    base = jnp.repeat(cache_valid[:, None, :], K, axis=1)          # [B, K, S]
    causal = jnp.tril(jnp.ones((K, K), bool))
    chunk_mask = causal[None] & chunk_valid[:, None, :] & chunk_valid[:, :, None]
    attn_mask = jax.lax.dynamic_update_slice(base, chunk_mask, (0, 0, write_pos))

    x = params["embed"][tokens]
    x, new_cache = _run_layers(
        params, spec, x, cos, sin, write_pos, cache, attn_mask, impl,
        chunk=True, ring=ring,
    )
    # Per-row last valid chunk position -> one LM-head application.
    last = jnp.sum(chunk_valid.astype(jnp.int32), axis=1) - 1      # [B]
    h_last = jnp.take_along_axis(x, last[:, None, None], axis=1)   # [B, 1, D]
    logits = _logits(params, spec, h_last)[:, 0, :]
    return logits, new_cache


def decode_chunk_spec(
    params: TransformerParams,
    spec: ModelSpec,
    tokens: jax.Array,         # [B, K1] chunk: sampled token + draft
    chunk_valid: jax.Array,    # [B, K1] bool; position 0 always valid
    row_write_pos: jax.Array,  # [B] int32: PER-ROW cache slot of chunk col 0
    positions: jax.Array,      # [B, K1] RoPE positions (per-row real counts)
    cache: Dict,
    cache_valid: jax.Array,    # [B, S] attendable cache slots BEFORE chunk
    impl: str = "xla",
    ring=None,                 # static (Mesh, axis_name): sp-sharded-cache
                               # chunk decode (sp_chunk_decode_attention)
) -> Tuple[jax.Array, Dict]:
    """One speculative-decoding verify step: process a [B, K1] chunk
    (the sampled token at position 0 plus up to K1-1 drafted tokens)
    against the cache, with PER-ROW write positions (each row's cache
    stays fully compacted at its own accepted-token count) and logits
    returned at EVERY chunk position — position j's logits are the
    model's distribution for position j+1, which is what the acceptance
    test compares each draft token against.

    Differs from :func:`decode_chunk` in exactly two ways: the KV write
    is a per-row scatter (``_write_cache`` [B]-pos form) and the LM head
    applies to all K1 positions instead of the last valid one.  The
    attention itself is mask-driven and shared.
    """
    B, K1 = tokens.shape
    cos, sin = rope_table(positions, spec.head_dim, spec.rope_theta,
                          spec.rope_scaling)

    # Mask: chunk queries attend valid prior cache slots plus the
    # causally-visible valid chunk prefix, scattered at per-row columns.
    S = cache_valid.shape[1]
    base = jnp.repeat(cache_valid[:, None, :], K1, axis=1)         # [B, K1, S]
    causal = jnp.tril(jnp.ones((K1, K1), bool))
    chunk_mask = causal[None] & chunk_valid[:, None, :] & chunk_valid[:, :, None]
    bidx = jnp.arange(B)[:, None, None]
    qidx = jnp.arange(K1)[None, :, None]
    sidx = row_write_pos[:, None, None] + jnp.arange(K1)[None, None, :]
    attn_mask = base.at[bidx, qidx, sidx].set(chunk_mask)

    x = params["embed"][tokens]
    x, new_cache = _run_layers(
        params, spec, x, cos, sin, row_write_pos, cache, attn_mask, impl,
        chunk=True, ring=ring,
    )
    logits = _logits(params, spec, x)                              # [B, K1, V]
    return logits, new_cache


def _block_chunk(
    layer: Dict,
    spec: ModelSpec,
    x: jax.Array,              # [B, K, D]
    cos, sin,
    write_pos: jax.Array,
    cache_entry: Dict,
    attn_mask: jax.Array,      # [B, K, S]
    impl: str,
    ring=None,                 # static (Mesh, axis_name): sp-sharded-cache
                               # chunk decode (sp_chunk_decode_attention)
) -> Tuple[jax.Array, Dict]:
    """Chunk decode block: write the fresh K positions into the cache,
    then attend over the WHOLE cache (prior context + the chunk itself,
    all selected by ``attn_mask``)."""
    B, K, D = x.shape
    h = rms_norm(x, layer["attn_norm"], spec.rms_eps)
    q, k, v = dense(h, layer["wq"]), dense(h, layer["wk"]), dense(h, layer["wv"])
    if "bq" in layer:
        q, k, v = q + layer["bq"], k + layer["bk"], v + layer["bv"]
    q = q.reshape(B, K, spec.num_heads, spec.head_dim)
    k = k.reshape(B, K, spec.num_kv_heads, spec.head_dim)
    v = v.reshape(B, K, spec.num_kv_heads, spec.head_dim)
    if spec.qk_norm:
        q = rms_norm(q, layer["q_norm"], spec.rms_eps)
        k = rms_norm(k, layer["k_norm"], spec.rms_eps)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    new_entry = _write_cache(cache_entry, k, v, write_pos)

    # Attend over the full cache including the just-written chunk.
    scale = 1.0 / math.sqrt(spec.head_dim)
    quantized = "k_scale" in new_entry
    if "tbl" in new_entry:
        # Paged cache (chunk form — the fast-forward / speculative-
        # verify decode windows; paged chunked PREFILL attends via
        # ``_block``'s cached-prefix path instead): ``impl`` carries
        # the engine-resolved paged marker — the fused kernel, or
        # "xla" = gather to the dense layout and attend.  Either way
        # the PAGED entry returns for the carry; see
        # ops/paged_attention.py.
        from bcg_tpu.ops.paged_attention import paged_chunk_attention

        attn_out = paged_chunk_attention(
            q, new_entry, attn_mask, scale, impl=impl
        )
    elif ring is not None:
        # Sequence-parallel chunk decode: cache stays sharded over sp,
        # partials merge via pmax/psum (same loud-on-indivisible policy
        # as the single-token path — the engine sp-aligns its caches).
        # Takes precedence over the single-device Pallas kernel: with
        # sp>1 the replicated full-cache kernel would defeat the
        # sharding.  An int8 cache dequantizes its local slice only.
        from bcg_tpu.ops.ring_attention import sp_chunk_decode_attention

        assert not kv_is_int4(new_entry), (
            "int4 KV does not compose with sp-sharded decode (the ring "
            "kernels dequantize int8 scales) — the engine rejects the "
            "pairing at boot"
        )
        mesh, axis_name = ring
        attn_out = sp_chunk_decode_attention(
            q, new_entry["k"], new_entry["v"], attn_mask, mesh,
            axis_name=axis_name, scale=scale,
            k_scale=new_entry.get("k_scale"),
            v_scale=new_entry.get("v_scale"),
        )
    elif quantized and impl == "pallas" and jax.default_backend() == "tpu" \
            and spec.head_dim % 128 == 0 and not kv_is_int4(new_entry):
        # int8 cache: stream once, dequantize in VMEM (K*group query rows
        # per program — the prefill flash kernel would pad K chunk rows
        # to a 128-row block).  The packed-int4 slab takes the dequant
        # fallback below (the engine never resolves "pallas" for it).
        from bcg_tpu.ops.decode_attention import chunk_decode_attention

        attn_out = chunk_decode_attention(
            q, new_entry["k"], new_entry["v"], attn_mask, scale,
            k_scale=new_entry["k_scale"], v_scale=new_entry["v_scale"],
        )
    else:
        ck, cv = new_entry["k"], new_entry["v"]
        if quantized:
            dequantize_kv = _kv_dequantizer(new_entry)

            # Slow fallback (off-TPU / unaligned head dim): full dequant
            # out of the [B, Hkv, S, Dh(/2 packed)] storage layout.
            ck = dequantize_kv(
                ck, new_entry["k_scale"]).transpose(0, 2, 1, 3).astype(q.dtype)
            cv = dequantize_kv(
                cv, new_entry["v_scale"]).transpose(0, 2, 1, 3).astype(q.dtype)
        attn_out = attention(
            q, ck, cv, attn_mask, scale, "xla" if quantized else impl
        )
    x = x + dense(attn_out.reshape(B, K, spec.q_size), layer["wo"])

    h = rms_norm(x, layer["mlp_norm"], spec.rms_eps)
    gate = jax.nn.silu(dense(h, layer["w_gate"]))
    x = x + dense(gate * dense(h, layer["w_up"]), layer["w_down"])
    return x, new_entry


def param_count(params: TransformerParams) -> int:
    return sum(p.size for p in jax.tree.leaves(params))
