"""Functional decoder-only transformer (RMSNorm / RoPE / GQA / SwiGLU).

TPU-first design choices:

* Parameters are a plain dict pytree; :mod:`bcg_tpu.parallel.sharding`
  assigns ``NamedSharding`` per leaf (heads and the MLP intermediate dim
  partition over the ``tp`` mesh axis — Megatron layout: column-parallel
  in-projections, row-parallel out-projections).
* Static shapes everywhere: prefill is [B, L] with an explicit validity
  mask (left-padded batches), decode is a [B, 1] step against a
  preallocated KV cache updated via ``dynamic_update_slice``.
* Weights and KV cache are bf16; RMSNorm accumulates in f32; attention
  logits/softmax run in f32 for stability.
* The attention inner op is pluggable (``attention_impl``): the stock
  XLA path (einsum softmax einsum — XLA fuses it well on MXU) or the
  Pallas flash kernel in :mod:`bcg_tpu.ops.attention`.

Replaces the CUDA side of the reference's engine (vLLM internals behind
``vllm_agent.py:100-157``); no reference code exists at this layer.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from bcg_tpu.models.configs import ModelSpec

TransformerParams = Dict  # pytree: see init_params for the layout


# ----------------------------------------------------------------- building

def init_params(
    spec: ModelSpec, key: jax.Array, dtype=jnp.bfloat16
) -> TransformerParams:
    """Random-init parameters with the HF-compatible logical layout.

    Layout (per layer l):
      embed            [V, D]
      layers.l.attn_norm [D]
      layers.l.wq      [D, H*Dh]    layers.l.wk/wv [D, Hkv*Dh]
      layers.l.wo      [H*Dh, D]
      layers.l.q_norm/k_norm [Dh]   (qk_norm models only)
      layers.l.mlp_norm [D]
      layers.l.w_gate/w_up [D, F]   layers.l.w_down [F, D]
      final_norm       [D]
      lm_head          [D, V]       (absent when tie_embeddings)
    """
    keys = iter(jax.random.split(key, 4 + spec.num_layers * 7))

    def dense(k, shape):
        fan_in = shape[0]
        return (jax.random.normal(k, shape, jnp.float32) / math.sqrt(fan_in)).astype(dtype)

    params: Dict = {
        "embed": dense(next(keys), (spec.vocab_size, spec.hidden_size)),
        "final_norm": jnp.ones((spec.hidden_size,), dtype),
        "layers": [],
    }
    for _ in range(spec.num_layers):
        layer = {
            "attn_norm": jnp.ones((spec.hidden_size,), dtype),
            "wq": dense(next(keys), (spec.hidden_size, spec.q_size)),
            "wk": dense(next(keys), (spec.hidden_size, spec.kv_size)),
            "wv": dense(next(keys), (spec.hidden_size, spec.kv_size)),
            "wo": dense(next(keys), (spec.q_size, spec.hidden_size)),
            "mlp_norm": jnp.ones((spec.hidden_size,), dtype),
            "w_gate": dense(next(keys), (spec.hidden_size, spec.intermediate_size)),
            "w_up": dense(next(keys), (spec.hidden_size, spec.intermediate_size)),
            "w_down": dense(next(keys), (spec.intermediate_size, spec.hidden_size)),
        }
        if spec.qk_norm:
            layer["q_norm"] = jnp.ones((spec.head_dim,), dtype)
            layer["k_norm"] = jnp.ones((spec.head_dim,), dtype)
        params["layers"].append(layer)
    if not spec.tie_embeddings:
        params["lm_head"] = dense(next(keys), (spec.hidden_size, spec.vocab_size))
    return params


# ------------------------------------------------------------------ kernels

def rms_norm(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    x32 = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * scale).astype(x.dtype) * weight


def rope_table(positions: jax.Array, head_dim: int, theta: float) -> Tuple[jax.Array, jax.Array]:
    """cos/sin tables for the given positions ([..., P] -> [..., P, Dh/2])."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    angles = positions.astype(jnp.float32)[..., None] * inv_freq
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotate half (HF convention). x: [B, T, H, Dh]; cos/sin: [B, T, Dh/2]."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    cos = cos[:, :, None, :]
    sin = sin[:, :, None, :]
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _xla_attention(q, k, v, mask, scale):
    """Stock attention: einsum -> masked f32 softmax -> einsum.

    q: [B, T, H, Dh], k/v: [B, S, Hkv, Dh], mask: [B, T, S] bool.
    """
    B, T, H, Dh = q.shape
    Hkv = k.shape[2]
    group = H // Hkv
    qg = q.reshape(B, T, Hkv, group, Dh)
    logits = jnp.einsum("bthgd,bshd->bhgts", qg, k).astype(jnp.float32) * scale
    logits = jnp.where(mask[:, None, None, :, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    out = jnp.einsum("bhgts,bshd->bthgd", probs, v)
    return out.reshape(B, T, H, Dh)


def attention(q, k, v, mask, scale, impl: str = "xla"):
    if impl == "pallas":
        from bcg_tpu.ops.attention import flash_attention

        return flash_attention(q, k, v, mask, scale)
    if impl == "blockwise":
        from bcg_tpu.ops.attention import blockwise_attention

        return blockwise_attention(q, k, v, mask, scale)
    return _xla_attention(q, k, v, mask, scale)


# ------------------------------------------------------------------ forward

def _block(
    layer: Dict,
    spec: ModelSpec,
    x: jax.Array,              # [B, T, D]
    cos: jax.Array,
    sin: jax.Array,
    kv_write_pos: jax.Array,   # scalar: where in the cache to write
    k_cache: jax.Array,        # [B, S, Hkv, Dh]
    v_cache: jax.Array,
    attn_mask: jax.Array,      # [B, T, S] over the cache
    impl: str,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    B, T, D = x.shape
    h = rms_norm(x, layer["attn_norm"], spec.rms_eps)
    q = (h @ layer["wq"]).reshape(B, T, spec.num_heads, spec.head_dim)
    k = (h @ layer["wk"]).reshape(B, T, spec.num_kv_heads, spec.head_dim)
    v = (h @ layer["wv"]).reshape(B, T, spec.num_kv_heads, spec.head_dim)
    if spec.qk_norm:
        q = rms_norm(q, layer["q_norm"], spec.rms_eps)
        k = rms_norm(k, layer["k_norm"], spec.rms_eps)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    k_cache = jax.lax.dynamic_update_slice(k_cache, k, (0, kv_write_pos, 0, 0))
    v_cache = jax.lax.dynamic_update_slice(v_cache, v, (0, kv_write_pos, 0, 0))

    scale = 1.0 / math.sqrt(spec.head_dim)
    attn_out = attention(q, k_cache, v_cache, attn_mask, scale, impl)
    x = x + attn_out.reshape(B, T, spec.q_size) @ layer["wo"]

    h = rms_norm(x, layer["mlp_norm"], spec.rms_eps)
    gate = jax.nn.silu(h @ layer["w_gate"])
    x = x + (gate * (h @ layer["w_up"])) @ layer["w_down"]
    return x, k_cache, v_cache


def _logits(params: TransformerParams, spec: ModelSpec, x: jax.Array) -> jax.Array:
    h = rms_norm(x, params["final_norm"], spec.rms_eps)
    head = params["embed"].T if spec.tie_embeddings else params["lm_head"]
    return (h @ head).astype(jnp.float32)


def init_kv_cache(spec: ModelSpec, batch: int, max_len: int, dtype=jnp.bfloat16):
    """Per-layer list of {k, v} leaves ([B, S, Hkv, Dh] each).

    Kept as separate pytree leaves (not one stacked array) so the
    ``dynamic_update_slice`` in each decode step is a pure per-buffer
    update XLA can alias in-place inside ``lax.while_loop`` — a stacked
    layout would force a gather + restack copy of the whole cache every
    token."""
    shape = (batch, max_len, spec.num_kv_heads, spec.head_dim)
    return [
        {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
        for _ in range(spec.num_layers)
    ]


def prefill(
    params: TransformerParams,
    spec: ModelSpec,
    tokens: jax.Array,        # [B, L] left-padded
    valid: jax.Array,         # [B, L] bool, False on pads
    cache: Dict,              # from init_kv_cache, written at [0, L)
    impl: str = "xla",
) -> Tuple[jax.Array, Dict]:
    """Process the full prompt; returns last-position logits and the cache.

    Left-padding: positions count only valid tokens, so RoPE sees each
    sequence starting at 0; pads are masked out of attention entirely.
    """
    B, L = tokens.shape
    S = cache[0]["k"].shape[1]
    positions = jnp.cumsum(valid.astype(jnp.int32), axis=1) - 1
    positions = jnp.maximum(positions, 0)
    cos, sin = rope_table(positions, spec.head_dim, spec.rope_theta)

    causal = jnp.tril(jnp.ones((L, L), bool))
    mask_ll = causal[None] & valid[:, None, :] & valid[:, :, None]  # [B, L, L]
    # Mask over the full cache length S (beyond L nothing is valid yet).
    attn_mask = jnp.zeros((B, L, S), bool).at[:, :, :L].set(mask_ll)

    x = params["embed"][tokens]
    new_cache = []
    for layer_idx, layer in enumerate(params["layers"]):
        x, k_l, v_l = _block(
            layer, spec, x, cos, sin, jnp.int32(0),
            cache[layer_idx]["k"], cache[layer_idx]["v"], attn_mask, impl,
        )
        new_cache.append({"k": k_l, "v": v_l})
    logits = _logits(params, spec, x[:, -1:, :])[:, 0, :]  # [B, V]
    return logits, new_cache


def decode_step(
    params: TransformerParams,
    spec: ModelSpec,
    token: jax.Array,          # [B] current tokens
    write_pos: jax.Array,      # scalar int32: cache slot to write
    seq_positions: jax.Array,  # [B] RoPE positions of these tokens
    cache: Dict,
    valid_mask: jax.Array,     # [B, S] which cache slots are attendable
    impl: str = "xla",
) -> Tuple[jax.Array, Dict]:
    """One autoregressive step for the whole batch."""
    B = token.shape[0]
    cos, sin = rope_table(seq_positions[:, None], spec.head_dim, spec.rope_theta)
    x = params["embed"][token][:, None, :]  # [B, 1, D]
    attn_mask = valid_mask[:, None, :]      # [B, 1, S]

    new_cache = []
    for layer_idx, layer in enumerate(params["layers"]):
        x, k_l, v_l = _block(
            layer, spec, x, cos, sin, write_pos,
            cache[layer_idx]["k"], cache[layer_idx]["v"], attn_mask, impl,
        )
        new_cache.append({"k": k_l, "v": v_l})
    logits = _logits(params, spec, x)[:, 0, :]
    return logits, new_cache


def param_count(params: TransformerParams) -> int:
    return sum(p.size for p in jax.tree.leaves(params))
