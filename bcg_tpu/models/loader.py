"""HF checkpoint loading: safetensors -> transformer param pytree.

Replaces the weight-loading half of the reference's engine boot
(``vllm_agent.py:100-157``).  Weights stream tensor-by-tensor from
safetensors shards into bf16 device arrays — optionally placed under a
``NamedSharding`` per leaf while loading, so a TP-sharded 32B model never
materializes unsharded on one host.

This build environment has no network egress, so checkpoints must exist
on local disk (HF cache layout or a flat directory of ``*.safetensors``).
"""

from __future__ import annotations

import math
import os
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np

from bcg_tpu.models.configs import ModelSpec
from bcg_tpu.runtime.envflags import get_str

# HF parameter name templates for the Qwen/Llama/Mistral family.
_LAYER_MAP = {
    "attn_norm": "model.layers.{i}.input_layernorm.weight",
    "wq": "model.layers.{i}.self_attn.q_proj.weight",
    "wk": "model.layers.{i}.self_attn.k_proj.weight",
    "wv": "model.layers.{i}.self_attn.v_proj.weight",
    "bq": "model.layers.{i}.self_attn.q_proj.bias",
    "bk": "model.layers.{i}.self_attn.k_proj.bias",
    "bv": "model.layers.{i}.self_attn.v_proj.bias",
    "wo": "model.layers.{i}.self_attn.o_proj.weight",
    "q_norm": "model.layers.{i}.self_attn.q_norm.weight",
    "k_norm": "model.layers.{i}.self_attn.k_norm.weight",
    "mlp_norm": "model.layers.{i}.post_attention_layernorm.weight",
    "w_gate": "model.layers.{i}.mlp.gate_proj.weight",
    "w_up": "model.layers.{i}.mlp.up_proj.weight",
    "w_down": "model.layers.{i}.mlp.down_proj.weight",
}
_TOP_MAP = {
    "embed": "model.embed_tokens.weight",
    "final_norm": "model.norm.weight",
    "lm_head": "lm_head.weight",
}
# HF stores projections as [out, in]; our layout is [in, out].
_TRANSPOSED = {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head"}


def find_checkpoint_dir(model_name: str) -> Optional[str]:
    """Locate a local checkpoint: explicit dir, HF cache, or env override."""
    candidates = []
    env = get_str("BCG_TPU_CHECKPOINT_DIR")
    if env:
        candidates.append(os.path.join(env, model_name.replace("/", "--")))
        candidates.append(env)
    candidates.append(model_name)  # model_name may itself be a path
    # Repo-local checkpoints (e.g. the hermetic bcg-hf/* artifact sets
    # built by models/hf_fixture.py).
    candidates.append(os.path.join("checkpoints", model_name.replace("/", "--")))
    hf_home = os.environ.get("HF_HOME", os.path.expanduser("~/.cache/huggingface"))
    snap_root = os.path.join(
        hf_home, "hub", f"models--{model_name.replace('/', '--')}", "snapshots"
    )
    if os.path.isdir(snap_root):
        for snap in sorted(os.listdir(snap_root)):
            candidates.append(os.path.join(snap_root, snap))
    for c in candidates:
        if c and os.path.isdir(c) and any(
            f.endswith(".safetensors") for f in os.listdir(c)
        ):
            return c
    return None


def load_checkpoint_params(
    spec: ModelSpec,
    model_name: str,
    mesh=None,
    dtype=jnp.bfloat16,
    leaf_transform=None,
    ckpt_dir: Optional[str] = None,
) -> Dict:
    """Load and (optionally) shard all parameters for ``spec``.

    ``leaf_transform(logical_name, tensor) -> leaf`` is applied to each
    tensor right after device placement — e.g. streamed int8 quantization
    (models/quantize.py:quantize_leaf_transform), which keeps peak device
    memory at the final model size instead of bf16 + quantized copies.
    ``ckpt_dir``: a pre-resolved checkpoint directory (skips the
    candidate walk a caller already did via :func:`find_checkpoint_dir`).
    """
    if ckpt_dir is None:
        ckpt_dir = find_checkpoint_dir(model_name)
    if ckpt_dir is None:
        raise FileNotFoundError(
            f"No local safetensors checkpoint found for {model_name!r} "
            "(zero-egress environment: download is not possible; set "
            "BCG_TPU_CHECKPOINT_DIR or use a bcg-tpu/* random-weight preset)"
        )
    from safetensors import safe_open

    # Index every tensor name to its shard file.
    shard_files = sorted(
        os.path.join(ckpt_dir, f)
        for f in os.listdir(ckpt_dir)
        if f.endswith(".safetensors")
    )
    name_to_file: Dict[str, str] = {}
    for path in shard_files:
        with safe_open(path, framework="numpy") as f:
            for name in f.keys():
                name_to_file[name] = path

    sharding_for = None
    if mesh is not None:
        from bcg_tpu.parallel.sharding import param_sharding

        sharding_for = lambda logical: param_sharding(logical, spec, mesh)  # noqa: E731

    open_files: Dict[str, object] = {}

    def fetch(hf_name: str, logical: str):
        path = name_to_file[hf_name]
        if path not in open_files:
            open_files[path] = safe_open(path, framework="numpy")
        arr = open_files[path].get_tensor(hf_name)
        return _convert(arr, logical)

    def _convert(arr, logical: str):
        # bf16 bit-pattern view, transpose, and dtype cast all happen on
        # the HOST ndarray, so the FIRST device placement is already the
        # sharded one — `jnp.asarray` first would stage the full tensor
        # unsharded on the default device, exactly the transient the
        # per-leaf sharded load exists to avoid.
        if arr.dtype == np.uint16:  # raw bf16 storage
            arr = arr.view(ml_dtypes.bfloat16)
        if logical.split(".")[-1] in _TRANSPOSED:
            arr = arr.T
        arr = arr.astype(np.dtype(dtype), copy=False)
        if sharding_for is not None:
            tensor = jax.device_put(arr, sharding_for(logical))
        else:
            tensor = jnp.asarray(arr)
        if leaf_transform is not None:
            tensor = leaf_transform(logical, tensor)
        return tensor

    params: Dict = {"layers": []}
    try:
        for logical, hf_name in _TOP_MAP.items():
            if logical == "lm_head" and spec.tie_embeddings:
                continue
            if hf_name not in name_to_file:
                if logical == "lm_head":
                    continue  # tied embeddings checkpoint
                raise KeyError(f"{hf_name} missing from checkpoint {ckpt_dir}")
            params[logical] = fetch(hf_name, logical)
        for i in range(spec.num_layers):
            layer = {}
            for logical, template in _LAYER_MAP.items():
                if logical in ("q_norm", "k_norm") and not spec.qk_norm:
                    continue
                if logical in ("bq", "bk", "bv") and not spec.attn_bias:
                    continue
                hf_name = template.format(i=i)
                layer[logical] = fetch(hf_name, f"layers.{i}.{logical}")
            params["layers"].append(layer)
    finally:
        # Release shard handles/mmaps deterministically.  safe_open
        # handles expose the context-manager protocol; some versions also
        # have .close() — prefer it, else call __exit__ with its three
        # required args.
        for handle in open_files.values():
            try:
                close = getattr(handle, "close", None)
                if close is not None:
                    close()
                else:
                    exit_ = getattr(handle, "__exit__", None)
                    if exit_ is not None:
                        exit_(None, None, None)
            except Exception:
                pass
        open_files.clear()
    return params


# -------------------------------------------------- born-sharded random init

def init_random_params_sharded(
    spec: ModelSpec,
    key: jax.Array,
    mesh=None,
    dtype=jnp.bfloat16,
    leaf_transform=None,
) -> Dict:
    """Born-sharded, born-quantized random init — the flagship-scale
    boot path (hermetic ``bcg-tpu/*`` presets and benches).

    ``transformer.init_params`` creates every leaf eagerly on the
    default device: an fp32 intermediate per tensor, unsharded — a 14B
    bf16 tree peaks far past one chip's HBM during init even when the
    mesh has room (the round-5 ``bench_14b`` RESOURCE_EXHAUSTED, twice).
    This materializes the SAME ``param_plan`` (same key consumption,
    bit-identical values) leaf by leaf through a jitted initializer with
    ``out_shardings=param_sharding(...)`` and the quantize
    ``leaf_transform`` INSIDE the jit, so:

    * no full-precision leaf ever exists unsharded — the fp32 source and
      its bf16/int8 product are computed per device shard;
    * peak device memory is the transformed tree so far plus ONE leaf's
      shard-sized transient (see ``boot_peak_report`` for the analytic
      accounting).

    ``leaf_transform`` must depend only on the LAST component of the
    logical name (true of ``quantize_leaf_transform``): per-leaf jits
    are reused across layers of the same shape, so a transform keyed on
    the layer index would silently apply layer 0's behaviour everywhere.

    With ``mesh=None`` the per-leaf jit still fuses the fp32
    intermediate away (single-device peak = tree + one leaf), matching
    the streamed-checkpoint discipline this replaces.

    Values are MESH-SHAPE-INVARIANT: the partitionable threefry RNG is
    enabled for the scope of this call, so the same seed yields the same
    weights at tp=1 and tp=8 (the legacy counter scheme re-derives
    per-shard streams under ``out_shardings`` — a tp=2 and a tp=4 bench
    would otherwise serve different random models).  They intentionally
    differ bit-wise from ``transformer.init_params``'s legacy-RNG
    output; no golden-value contract exists for random weights.
    """
    from bcg_tpu.models.transformer import assemble_param_tree, param_plan

    sharding_for = None
    if mesh is not None:
        from bcg_tpu.parallel.sharding import param_sharding

        sharding_for = lambda logical: param_sharding(logical, spec, mesh)  # noqa: E731

    plan = param_plan(spec)
    keys = jax.random.split(key, 4 + spec.num_layers * 7)
    spare_key = keys[-1]  # never consumed by the plan; feeds ones/zeros jits
    ki = 0
    fns: Dict = {}
    items = []
    prev_partitionable = jax.config.jax_threefry_partitionable
    jax.config.update("jax_threefry_partitionable", True)
    try:
        for logical, kind, shape in plan:
            leaf = logical.split(".")[-1]
            if kind == "dense":
                k = keys[ki]
                ki += 1
            else:
                k = spare_key

            cache_key = (leaf, kind, shape)
            fn = fns.get(cache_key)
            if fn is None:

                def _init(k, _kind=kind, _shape=shape, _logical=logical):
                    if _kind == "dense":
                        w = (
                            jax.random.normal(k, _shape, jnp.float32)
                            / math.sqrt(_shape[0])
                        ).astype(dtype)
                        # Dense leaves only, like init_params and
                        # boot_peak_report — the three param_plan
                        # consumers must agree on what transforms.
                        if leaf_transform is not None:
                            w = leaf_transform(_logical, w)
                        return w
                    if _kind == "ones":
                        return jnp.ones(_shape, dtype)
                    return jnp.zeros(_shape, dtype)

                out_shardings = None
                if sharding_for is not None:
                    out_struct = jax.eval_shape(_init, k)
                    if isinstance(out_struct, dict):  # quantized {"q","scale"}
                        out_shardings = {
                            sub: sharding_for(f"{logical}.{sub}")
                            for sub in out_struct
                        }
                    else:
                        out_shardings = sharding_for(logical)
                    fn = jax.jit(_init, out_shardings=out_shardings)
                else:
                    fn = jax.jit(_init)
                fns[cache_key] = fn
            items.append((logical, fn(k)))
    finally:
        jax.config.update("jax_threefry_partitionable", prev_partitionable)
    return assemble_param_tree(items)


def _shard_bytes(struct, sharding) -> int:
    """Per-device bytes of a ShapeDtypeStruct under a NamedSharding
    (full bytes when ``sharding`` is None) — the shared computation in
    ``parallel/sharding.shard_bytes``, so this analytic report and the
    engine's HBM budget cannot drift apart."""
    from bcg_tpu.parallel.sharding import shard_bytes

    return shard_bytes(struct.shape, struct.dtype, sharding)


def boot_peak_report(
    spec: ModelSpec,
    mesh=None,
    quantization: Optional[str] = None,
    dtype=jnp.bfloat16,
    scan_layers: bool = True,
) -> Dict:
    """Analytic per-device boot-memory accounting for the born-sharded
    init path — pure ``eval_shape`` + ``param_sharding``, NO weights
    materialized (safe for 14B/32B specs on a laptop CPU).

    Models the engine boot phase by phase:

    * per-leaf init: the already-materialized (transformed) tree so far,
      plus the current leaf's fp32 source and its transformed output —
      all at SHARD size, because ``init_random_params_sharded``'s
      ``out_shardings`` partition the whole per-leaf computation;
    * consume-stacking (``scan_layers``): the full transformed tree plus
      one leaf-group's stacked copy (``stack_layer_params(consume=True)``
      frees each group's per-layer sources as its stack appears).

    Returns a dict of byte counts; the headline invariant — boot peak
    per device <= final tree + one leaf-group (where "one leaf-group"
    is the larger of the biggest stacking group and the biggest single-
    leaf init transient) — holds by construction and is asserted by
    ``tests/test_born_sharded.py`` and ``scripts/boot_smoke.py`` against
    the components reported here.
    """
    from bcg_tpu.models.transformer import param_plan

    transform = None
    if quantization is not None:
        from bcg_tpu.models.quantize import quantize_leaf_transform

        transform = quantize_leaf_transform(spec, quantization)

    sharding_for = None
    if mesh is not None:
        from bcg_tpu.parallel.sharding import param_sharding

        sharding_for = lambda logical: param_sharding(logical, spec, mesh)  # noqa: E731

    done = 0
    init_peak = 0
    max_transient = 0
    max_transient_leaf = None
    group_bytes: Dict[str, int] = {}
    for logical, kind, shape in param_plan(spec):
        src_dtype = jnp.float32 if kind == "dense" else dtype

        def _make(w, _logical=logical, _kind=kind):
            w = w.astype(dtype)
            if transform is not None and _kind == "dense":
                return transform(_logical, w)
            return w

        src = jax.ShapeDtypeStruct(shape, src_dtype)
        out_struct = jax.eval_shape(_make, src)
        if isinstance(out_struct, dict):
            out_b = sum(
                _shard_bytes(
                    sub,
                    sharding_for(f"{logical}.{name}") if sharding_for else None,
                )
                for name, sub in out_struct.items()
            )
        else:
            out_b = _shard_bytes(
                out_struct, sharding_for(logical) if sharding_for else None
            )
        # The fp32 source transient is sharded like the parent weight
        # (out_shardings propagate back through the elementwise chain).
        transient = (
            _shard_bytes(src, sharding_for(logical) if sharding_for else None)
            if kind == "dense"
            else 0
        )
        init_peak = max(init_peak, done + transient + out_b)
        if transient + out_b > max_transient:
            max_transient = transient + out_b
            max_transient_leaf = logical
        done += out_b
        parts = logical.split(".")
        if parts[0] == "layers":
            group_bytes[parts[2]] = group_bytes.get(parts[2], 0) + out_b

    max_group = max(group_bytes.values()) if group_bytes else 0
    stack_peak = done + max_group if scan_layers else done
    return {
        "final_bytes_per_device": done,
        "init_peak_bytes_per_device": init_peak,
        "stack_peak_bytes_per_device": stack_peak,
        "peak_bytes_per_device": max(init_peak, stack_peak),
        "max_init_transient_bytes": max_transient,
        "max_init_transient_leaf": max_transient_leaf,
        "max_leaf_group_bytes": max_group,
        "devices": 1 if mesh is None else mesh.size,
        "quantization": quantization,
    }
