"""HF checkpoint loading: safetensors -> transformer param pytree.

Replaces the weight-loading half of the reference's engine boot
(``vllm_agent.py:100-157``).  Weights stream tensor-by-tensor from
safetensors shards into bf16 device arrays — optionally placed under a
``NamedSharding`` per leaf while loading, so a TP-sharded 32B model never
materializes unsharded on one host.

This build environment has no network egress, so checkpoints must exist
on local disk (HF cache layout or a flat directory of ``*.safetensors``).
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bcg_tpu.models.configs import ModelSpec

# HF parameter name templates for the Qwen/Llama/Mistral family.
_LAYER_MAP = {
    "attn_norm": "model.layers.{i}.input_layernorm.weight",
    "wq": "model.layers.{i}.self_attn.q_proj.weight",
    "wk": "model.layers.{i}.self_attn.k_proj.weight",
    "wv": "model.layers.{i}.self_attn.v_proj.weight",
    "bq": "model.layers.{i}.self_attn.q_proj.bias",
    "bk": "model.layers.{i}.self_attn.k_proj.bias",
    "bv": "model.layers.{i}.self_attn.v_proj.bias",
    "wo": "model.layers.{i}.self_attn.o_proj.weight",
    "q_norm": "model.layers.{i}.self_attn.q_norm.weight",
    "k_norm": "model.layers.{i}.self_attn.k_norm.weight",
    "mlp_norm": "model.layers.{i}.post_attention_layernorm.weight",
    "w_gate": "model.layers.{i}.mlp.gate_proj.weight",
    "w_up": "model.layers.{i}.mlp.up_proj.weight",
    "w_down": "model.layers.{i}.mlp.down_proj.weight",
}
_TOP_MAP = {
    "embed": "model.embed_tokens.weight",
    "final_norm": "model.norm.weight",
    "lm_head": "lm_head.weight",
}
# HF stores projections as [out, in]; our layout is [in, out].
_TRANSPOSED = {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head"}


def find_checkpoint_dir(model_name: str) -> Optional[str]:
    """Locate a local checkpoint: explicit dir, HF cache, or env override."""
    candidates = []
    env = os.environ.get("BCG_TPU_CHECKPOINT_DIR")
    if env:
        candidates.append(os.path.join(env, model_name.replace("/", "--")))
        candidates.append(env)
    candidates.append(model_name)  # model_name may itself be a path
    # Repo-local checkpoints (e.g. the hermetic bcg-hf/* artifact sets
    # built by models/hf_fixture.py).
    candidates.append(os.path.join("checkpoints", model_name.replace("/", "--")))
    hf_home = os.environ.get("HF_HOME", os.path.expanduser("~/.cache/huggingface"))
    snap_root = os.path.join(
        hf_home, "hub", f"models--{model_name.replace('/', '--')}", "snapshots"
    )
    if os.path.isdir(snap_root):
        for snap in sorted(os.listdir(snap_root)):
            candidates.append(os.path.join(snap_root, snap))
    for c in candidates:
        if c and os.path.isdir(c) and any(
            f.endswith(".safetensors") for f in os.listdir(c)
        ):
            return c
    return None


def load_checkpoint_params(
    spec: ModelSpec,
    model_name: str,
    mesh=None,
    dtype=jnp.bfloat16,
    leaf_transform=None,
    ckpt_dir: Optional[str] = None,
) -> Dict:
    """Load and (optionally) shard all parameters for ``spec``.

    ``leaf_transform(logical_name, tensor) -> leaf`` is applied to each
    tensor right after device placement — e.g. streamed int8 quantization
    (models/quantize.py:quantize_leaf_transform), which keeps peak device
    memory at the final model size instead of bf16 + quantized copies.
    ``ckpt_dir``: a pre-resolved checkpoint directory (skips the
    candidate walk a caller already did via :func:`find_checkpoint_dir`).
    """
    if ckpt_dir is None:
        ckpt_dir = find_checkpoint_dir(model_name)
    if ckpt_dir is None:
        raise FileNotFoundError(
            f"No local safetensors checkpoint found for {model_name!r} "
            "(zero-egress environment: download is not possible; set "
            "BCG_TPU_CHECKPOINT_DIR or use a bcg-tpu/* random-weight preset)"
        )
    from safetensors import safe_open

    # Index every tensor name to its shard file.
    shard_files = sorted(
        os.path.join(ckpt_dir, f)
        for f in os.listdir(ckpt_dir)
        if f.endswith(".safetensors")
    )
    name_to_file: Dict[str, str] = {}
    for path in shard_files:
        with safe_open(path, framework="numpy") as f:
            for name in f.keys():
                name_to_file[name] = path

    sharding_for = None
    if mesh is not None:
        from bcg_tpu.parallel.sharding import param_sharding

        sharding_for = lambda logical: param_sharding(logical, spec, mesh)  # noqa: E731

    open_files: Dict[str, object] = {}

    def fetch(hf_name: str, logical: str):
        path = name_to_file[hf_name]
        if path not in open_files:
            open_files[path] = safe_open(path, framework="numpy")
        arr = open_files[path].get_tensor(hf_name)
        return _convert(arr, logical)

    def _convert(arr, logical: str):
        if arr.dtype == np.uint16:  # raw bf16 storage
            arr = arr.view(np.uint16)
            tensor = jax.lax.bitcast_convert_type(jnp.asarray(arr), jnp.bfloat16)
        else:
            tensor = jnp.asarray(arr, dtype=dtype)
        if logical.split(".")[-1] in _TRANSPOSED:
            tensor = tensor.T
        tensor = tensor.astype(dtype)
        if sharding_for is not None:
            tensor = jax.device_put(tensor, sharding_for(logical))
        if leaf_transform is not None:
            tensor = leaf_transform(logical, tensor)
        return tensor

    params: Dict = {"layers": []}
    try:
        for logical, hf_name in _TOP_MAP.items():
            if logical == "lm_head" and spec.tie_embeddings:
                continue
            if hf_name not in name_to_file:
                if logical == "lm_head":
                    continue  # tied embeddings checkpoint
                raise KeyError(f"{hf_name} missing from checkpoint {ckpt_dir}")
            params[logical] = fetch(hf_name, logical)
        for i in range(spec.num_layers):
            layer = {}
            for logical, template in _LAYER_MAP.items():
                if logical in ("q_norm", "k_norm") and not spec.qk_norm:
                    continue
                if logical in ("bq", "bk", "bv") and not spec.attn_bias:
                    continue
                hf_name = template.format(i=i)
                layer[logical] = fetch(hf_name, f"layers.{i}.{logical}")
            params["layers"].append(layer)
    finally:
        # Release shard handles/mmaps deterministically.  safe_open
        # handles expose the context-manager protocol; some versions also
        # have .close() — prefer it, else call __exit__ with its three
        # required args.
        for handle in open_files.values():
            try:
                close = getattr(handle, "close", None)
                if close is not None:
                    close()
                else:
                    exit_ = getattr(handle, "__exit__", None)
                    if exit_ is not None:
                        exit_(None, None, None)
            except Exception:
                pass
        open_files.clear()
    return params
