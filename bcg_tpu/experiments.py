"""Paper-experiment presets (reference README.md:55-70).

The reference documents its Q1/Q2 experiments as raw CLI invocations;
this module makes them first-class, repeatable presets with multi-run
aggregation — plus the BASELINE.json sweep configs the reference never
scripted:

    python -m bcg_tpu.experiments q1-baseline --backend fake --runs 5
    python -m bcg_tpu.experiments q2 --model qwen3-14b
    python -m bcg_tpu.experiments scale-sweep --agents 16,32,64

Each run goes through :func:`bcg_tpu.api.run_simulation` (no files
written); the aggregate summary (consensus rate, mean rounds, Q2 quality
scores) prints as JSON so sweeps are scriptable.
"""

from __future__ import annotations

import argparse
import json
import statistics
from dataclasses import dataclass
from typing import Dict, List, Optional

from bcg_tpu.api import run_simulation


@dataclass(frozen=True)
class Preset:
    name: str
    description: str
    honest: int
    byzantine: int
    awareness: str
    max_rounds: int = 50


# Reference README.md:57-70 ("Reproducing Paper Experiments") plus the
# driver's BASELINE.json configs.
PRESETS: Dict[str, Preset] = {
    "q1-baseline": Preset(
        "q1-baseline",
        "Q1 cooperative: 4 honest, none_exist prompt (CPU-runnable smoke)",
        honest=4, byzantine=0, awareness="none_exist",
    ),
    "q1-full": Preset(
        "q1-full",
        "Q1 cooperative: 8 honest, may_exist prompt",
        honest=8, byzantine=0, awareness="may_exist",
    ),
    "q2": Preset(
        "q2",
        "Q2 resilience: 8 honest + 2 Byzantine, may_exist prompt",
        honest=8, byzantine=2, awareness="may_exist",
    ),
}


def _mean(xs: List[float]) -> Optional[float]:
    xs = [x for x in xs if x is not None]
    return round(statistics.mean(xs), 4) if xs else None


def aggregate(metrics: List[Dict]) -> Dict:
    """Cross-run summary over per-run ``get_statistics()`` payloads —
    the distribution-level view SURVEY.md §7 calls for (the reference is
    unseeded + temperature-sampled, so parity lives in aggregates, not
    transcripts)."""
    return {
        "runs": len(metrics),
        "consensus_rate": _mean([float(m.get("consensus_reached", False)) for m in metrics]),
        "mean_rounds": _mean([m.get("total_rounds") for m in metrics]),
        "mean_convergence_speed": _mean([m.get("convergence_speed") for m in metrics]),
        "mean_quality_score": _mean([m.get("consensus_quality_score") for m in metrics]),
        "mean_centrality": _mean([m.get("centrality") for m in metrics]),
        "byzantine_infiltration_rate": _mean(
            [float(m["byzantine_infiltration"])
             for m in metrics if m.get("byzantine_infiltration") is not None]
        ),
        "outcomes": sorted(
            {str(m.get("consensus_outcome")) for m in metrics}
        ),
    }


def run_preset(
    preset: Preset,
    runs: int = 1,
    model_name: Optional[str] = None,
    backend: Optional[str] = None,
    max_rounds: Optional[int] = None,
    seed: Optional[int] = 0,
    honest: Optional[int] = None,
    byzantine: Optional[int] = None,
    concurrency: int = 1,
    fault_rate: float = 0.0,
    drop_prob: float = 0.0,
    fake_policy: Optional[str] = None,
) -> Dict:
    """Run a preset ``runs`` times and aggregate.

    ``concurrency > 1`` runs that many games at once against ONE shared
    engine, merged into single device batches per phase
    (engine/collective.py) — decode cost is per-step weight streaming, so
    G concurrent games cost roughly one game's wall-clock.  The reference
    has no equivalent: its sweeps are sequential CLI invocations
    (README.md:55-70).

    ``fault_rate`` corrupts that fraction of LLM responses per run
    (engine/fault.py); ``drop_prob`` routes the games over the lossy
    channel (comm/lossy_sim.py) with that per-message drop probability —
    together they make resilience-vs-fault curves (LLM-side and
    channel-side) one-flag sweeps.
    """
    import dataclasses

    from bcg_tpu.api import resolve_engine_config
    from bcg_tpu.config import BCGConfig, CommunicationConfig

    n_honest = honest if honest is not None else preset.honest
    n_byz = byzantine if byzantine is not None else preset.byzantine
    engine_cfg = dataclasses.replace(
        resolve_engine_config(model_name, backend), fault_rate=fault_rate
    )
    if fake_policy is not None:
        engine_cfg = dataclasses.replace(engine_cfg, fake_policy=fake_policy)
    base_cfg = dataclasses.replace(BCGConfig(), engine=engine_cfg)
    if drop_prob:
        # Fail BEFORE any engine boot (same invariant as fault_rate,
        # engine/interface.py): a config typo must not cost a multi-GB
        # weight load first.
        if not 0.0 <= drop_prob <= 1.0:
            raise ValueError(f"drop_prob={drop_prob}: expected [0, 1]")
        base_cfg = dataclasses.replace(
            base_cfg,
            communication=CommunicationConfig(
                protocol_type="lossy_sim", drop_prob=drop_prob
            ),
        )

    def make_run(r: int):
        def go(engine=None):
            return run_simulation(
                n_agents=n_honest + n_byz,
                byzantine_count=n_byz,
                max_rounds=max_rounds if max_rounds is not None else preset.max_rounds,
                byzantine_awareness=preset.awareness,
                model_name=model_name,
                backend=backend,
                seed=None if seed is None else seed + r,
                engine=engine,
                config=base_cfg,
            )
        return go

    if concurrency > 1:
        from bcg_tpu.engine.interface import create_engine
        from bcg_tpu.runtime import envflags

        engine = create_engine(engine_cfg)
        try:
            if envflags.get_bool("BCG_TPU_SERVE"):
                # Arrival-driven serving scheduler (bcg_tpu/serve): no
                # lockstep waves — all runs start, at most `concurrency`
                # execute at once, and a straggler delays only itself.
                from bcg_tpu.serve import run_serving_simulations

                outs = run_serving_simulations(
                    engine, [make_run(r) for r in range(runs)],
                    max_concurrent=concurrency,
                    # Supervisor rebuild hook: a hang past the (env-
                    # gated) watchdog reboots the engine from the same
                    # config instead of killing the whole sweep.
                    engine_factory=lambda: create_engine(engine_cfg),
                )
            else:
                from bcg_tpu.engine.collective import run_concurrent_simulations

                outs = run_concurrent_simulations(
                    engine, [make_run(r) for r in range(runs)], concurrency
                )
        finally:
            engine.shutdown()
        failures = [o for o in outs if isinstance(o, BaseException)]
        if failures:
            raise failures[0]
        per_run = [o["metrics"] for o in outs]
    else:
        per_run = [make_run(r)()["metrics"] for r in range(runs)]
    return {"preset": preset.name, "aggregate": aggregate(per_run), "per_run": per_run}


def run_scale_sweep(
    agent_counts: List[int],
    byzantine_fraction: float = 0.0,
    **kwargs,
) -> List[Dict]:
    """BASELINE.json config 4: growing agent populations (one-agent-per-
    chip on real pods via the SPMD game step; batched on one chip here)."""
    results = []
    for n in agent_counts:
        byz = int(n * byzantine_fraction)
        p = Preset(f"scale-{n}", f"{n - byz}H+{byz}B", honest=n - byz,
                   byzantine=byz, awareness="may_exist")
        results.append(run_preset(p, **kwargs))
    return results


def run_model_sweep(models: List[str], **kwargs) -> List[Dict]:
    """BASELINE.json config 5: the Q2 mixed honest/Byzantine population
    swept across model families (each model boots its own engine; the
    reference would re-run its CLI per `MODEL_PRESETS` entry,
    config.py:20-30)."""
    results = []
    for m in models:
        r = run_preset(PRESETS["q2"], model_name=m, **kwargs)
        r["preset"] = f"model-sweep:{m}"
        results.append(r)
    return results


def main(argv: Optional[List[str]] = None) -> None:
    p = argparse.ArgumentParser(description="BCG paper-experiment presets")
    p.add_argument("preset", choices=[*PRESETS, "scale-sweep", "model-sweep"])
    p.add_argument("--runs", type=int, default=1)
    p.add_argument("--model", type=str, default=None)
    p.add_argument("--backend", type=str, default=None, choices=["jax", "fake"])
    p.add_argument("--rounds", type=int, default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--agents", type=str, default="16,32,64",
                   help="scale-sweep agent counts, comma-separated")
    p.add_argument("--models", type=str,
                   default="Qwen/Qwen3-32B,mistralai/Mistral-Small-Instruct-2409",
                   help="model-sweep model names, comma-separated "
                        "(BASELINE.json config 5)")
    p.add_argument("--byzantine-fraction", type=float, default=0.0,
                   help="scale-sweep Byzantine share of each population")
    p.add_argument("--concurrency", type=int, default=1,
                   help="Games run at once against one shared engine "
                        "(merged device batches; bound by KV-cache memory)")
    p.add_argument("--fault-rate", type=float, default=0.0,
                   help="Corrupt this fraction of LLM responses per run "
                        "(resilience-vs-fault-rate sweeps)")
    p.add_argument("--drop-prob", type=float, default=0.0,
                   help="Route games over the lossy channel with this "
                        "per-message drop probability "
                        "(resilience-vs-loss sweeps)")
    p.add_argument("--fake-policy", type=str, default=None,
                   help="Fake-backend scripted policy, e.g. "
                        "mixed:consensus:oscillate (adversary-strategy "
                        "sweeps without any LLM; engine/fake.py)")
    args = p.parse_args(argv)

    sweep_models = (
        args.models.split(",") if args.preset == "model-sweep" else []
    )
    for name in [args.model, *sweep_models]:
        if name and name.startswith("bcg-hf/"):
            # Hermetic HF fixtures materialize on demand (idempotent),
            # the same as bench.py — a parity sweep must not depend on
            # an earlier bench having built the checkpoint.
            from bcg_tpu.models.hf_fixture import build_checkpoint

            build_checkpoint(name)

    common = dict(runs=args.runs, model_name=args.model, backend=args.backend,
                  max_rounds=args.rounds, seed=args.seed,
                  concurrency=args.concurrency, fault_rate=args.fault_rate,
                  drop_prob=args.drop_prob, fake_policy=args.fake_policy)
    if args.preset == "scale-sweep":
        out = run_scale_sweep(
            [int(x) for x in args.agents.split(",")],
            byzantine_fraction=args.byzantine_fraction, **common,
        )
        print(json.dumps([{k: r[k] for k in ("preset", "aggregate")} for r in out], indent=2))
    elif args.preset == "model-sweep":
        if common.pop("model_name"):
            p.error("model-sweep takes --models (a comma-separated list), "
                    "not --model")
        models = [m.strip() for m in args.models.split(",") if m.strip()]
        out = run_model_sweep(models, **common)
        print(json.dumps([{k: r[k] for k in ("preset", "aggregate")} for r in out], indent=2))
    else:
        out = run_preset(PRESETS[args.preset], **common)
        print(json.dumps({"preset": out["preset"], "aggregate": out["aggregate"]}, indent=2))


if __name__ == "__main__":
    main()
