"""Compile-cost observability (``BCG_TPU_COMPILE_OBS``) + profiler
capture windows (``BCG_TPU_PROFILE`` / ``BCG_TPU_PROFILE_ROUNDS``).

ROADMAP item 1 fuses the whole consensus round into one
``lax``-controlled jit entry, which makes COMPILATION the next dominant
invisible cost: the ``engine.compile.<entry>`` / ``engine.retrace.<entry>``
counters (PR 4) say *that* a trace-cache miss happened but never *why*
or *how long it took*, and the sweep tier multiplies distinct jit
signatures across tenants.  This module closes the gap the way
``obs/hostsync.py`` closed it for device->host transfers: observe,
attribute, drift-gate.

Mechanics — the engine's trace-cache-miss seams feed two records here:

* **Signature events.**  ``jax_engine._note_jit_shape`` (the compile/
  retrace accounting keyed by (entry point, shape signature)) calls
  :func:`note_signature` with the new signature AND the entry's prior
  signatures.  A first signature is a ``first_compile``; any later one
  is a ``retrace``, and the observer diffs it arg-by-arg against the
  NEAREST cached signature (same arity, fewest differing positions,
  most recent on ties) to emit exactly ONE structured retrace-cause
  record: which argument changed (``max_new 32→48``), classified into
  the cause taxonomy (``shape`` / ``dtype`` / ``static_knob`` /
  ``path`` / ``arity``) — ``engine.retrace_cause.<kind>`` counters plus
  a JSONL event through the bounded
  :class:`~bcg_tpu.obs.export.EventSink` when the flag value is a path.
  Cause records are attributed span-first (the innermost open tracer
  span), then jit-entry (``jit_<entry>``) — the hostsync attribution
  ladder.
* **Compile timings.**  The compile-triggering call sites wrap
  themselves in :func:`time_block`; a block whose entry has a pending
  signature event (decode loops note BEFORE their first invocation,
  ``timing="pending"``) or whose elapsed the immediately following
  note consumes (prefill notes AFTER its dispatch, ``timing="stash"``)
  records its wall time into the per-entry
  ``engine.compile_ms.<entry>`` histogram, split into the cumulative
  ``engine.compile_obs.first_compile_ms`` / ``.retrace_ms`` counters.
  The ordering is declared BY the seam, never inferred: a
  ``"pending"`` note discards any stale steady-state stash instead of
  consuming it, so a retrace that follows warm dispatches times the
  actual compile, not the previous call's execute.  The measured
  window is the first dispatch of the new signature — trace + lower +
  compile run synchronously inside it (execution may overlap
  asynchronously; on the hermetic CPU gate the compile dominates).
  The AOT lower+compile the HLO census pays per entry (``obs/hlo.py``)
  is a REAL extra compile and is charged under its OWN histogram name
  (:func:`measure_aot` → ``engine.compile_ms.aot_<entry>`` plus the
  cumulative ``engine.compile_obs.aot_ms``) — never mixed into the
  serving entry's histogram, whose dispatch window already contains
  the AOT wall time when both flags are on.
* **Cache gauges.**  ``engine.compile_obs.cache_entries`` counts every
  distinct (engine, entry, signature) the observer has seen — the
  trace-cache population a sweep's per-tenant signatures multiply.

Profiler capture windows: ``BCG_TPU_PROFILE=<dir>`` +
``BCG_TPU_PROFILE_ROUNDS=a-b`` wrap ``jax.profiler`` around orchestrator
rounds (and serve dispatches) ``a..b`` — ONE bounded window per process,
Perfetto-loadable next to the Chrome tracer export, with a
``manifest.json`` stamped with the fleet identity
(:func:`bcg_tpu.obs.export.run_manifest`) so a captured trace is
attributable to its run without out-of-band bookkeeping.  The first
round/dispatch stream to reach ``a`` owns the window; it closes after
``b`` (or at interpreter exit, so a short run never leaves the profiler
running).

Zero surface when off (the hostsync idiom, pinned byte-exact by
tests/test_compile_obs.py): flags are read ONCE at first use, nothing
is registered, no threads start, and every module entry point degrades
to a shared no-op.  No jax import at module scope — loadable by
flag-only consumers (bench.py's error path, the import-free scripts'
subprocess tests); jax is touched only inside the profiler window, and
only when it actually starts.
"""

from __future__ import annotations

import re
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from bcg_tpu.obs import counters as obs_counters
from bcg_tpu.obs import tracer as obs_tracer
from bcg_tpu.runtime import envflags

# Attribution fragments must stay inside the metric-name taxonomy
# (BCG-OBS-NAME): span names like ``serve.request`` flatten to
# ``serve_request`` (the hostsync sanitizer).
_SANITIZE_RE = re.compile(r"[^a-z0-9_]")

# Per-entry compile-time histogram bounds (milliseconds).  The ladder
# resolves both the tiny-test CPU gate's sub-second compiles and a
# remote 8B boot's minutes-scale first compile.
COMPILE_MS_BOUNDS = (
    1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0,
    5000.0, 10000.0, 30000.0, 60000.0, 120000.0,
)

# The cause taxonomy (DESIGN.md "Compile observability").  Every
# counted retrace carries exactly one primary cause from this set.
CAUSE_KINDS = ("shape", "dtype", "static_knob", "path", "arity")

# Signature argument names classified as static knobs: python-level
# loop-builder parameters, not array shapes.  A numeric delta in any
# OTHER argument (batch, window, cache length) is a shape change.
_KNOB_NAMES = frozenset(
    {"max_new", "top_p", "spec_k", "spec_ngram", "attn_impl",
     "sampler_impl"}
)
_DTYPE_RE = re.compile(
    r"^(bf16|bfloat16|f16|float16|f32|float32|f64|float64|int4|int8|"
    r"int16|int32|int64|uint8|bool)$"
)

# Bounded in-memory cause-record window (the LAST_COMPILE_OBS /
# test-assertion surface; the JSONL sink carries the unbounded stream).
CAUSE_RING = 256


def _sanitize(name: str) -> str:
    return _SANITIZE_RE.sub("_", name.lower())


class _NullCm:
    """Shared no-op context manager — the disabled fast path (the
    hostsync ``_NullEntry`` idiom)."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL_CM = _NullCm()


# ------------------------------------------------------- signature diffing
def _classify_delta(name: str, old: Any, new: Any) -> str:
    """Primary cause kind for one changed signature argument."""
    if name == "path":
        return "path"
    if (isinstance(old, str) and isinstance(new, str)
            and (_DTYPE_RE.match(old) or _DTYPE_RE.match(new))):
        return "dtype"
    if name in _KNOB_NAMES:
        return "static_knob"
    if isinstance(old, tuple) and isinstance(new, tuple):
        if len(old) != len(new):
            return "shape"
        for o, n in zip(old, new):
            if o != n:
                return _classify_delta(name, o, n)
        return "shape"
    if isinstance(old, (int, float)) and isinstance(new, (int, float)):
        return "shape"
    return "static_knob"


def _arg_name(index: int, names: Optional[Sequence[str]]) -> str:
    if names is not None and index < len(names):
        return names[index]
    return f"arg{index}"


def diff_signature(
    sig: Tuple, prior: Sequence[Tuple],
    names: Optional[Sequence[str]] = None,
) -> Dict[str, Any]:
    """One structured cause for a retraced signature: the NEAREST prior
    signature (same arity, fewest differing positions, most recent on
    ties — ``prior`` is in insertion order) diffed arg-by-arg.  Returns
    ``{"cause", "arg", "old", "new", "changed": [...]}`` where ``arg``/
    ``old``/``new`` describe the PRIMARY (first) differing argument and
    ``changed`` lists every differing argument name.  No same-arity
    prior ⇒ cause ``arity`` (the signature tuple itself changed shape,
    e.g. a prefill path switch between the 4- and 5-tuple forms)."""
    same_arity = [p for p in prior if len(p) == len(sig)]
    if not same_arity:
        nearest = prior[-1]
        return {
            "cause": "arity",
            "arg": "signature",
            "old": len(nearest),
            "new": len(sig),
            "changed": ["signature"],
        }
    best: Optional[Tuple] = None
    best_diffs: List[int] = []
    for cand in same_arity:  # later wins ties: <= keeps the most recent
        diffs = [i for i, (o, n) in enumerate(zip(cand, sig)) if o != n]
        if best is None or len(diffs) <= len(best_diffs):
            best, best_diffs = cand, diffs
    if not best_diffs:  # defensive: caller only diffs genuinely new sigs
        return {"cause": "static_knob", "arg": "signature",
                "old": None, "new": None, "changed": []}
    i = best_diffs[0]
    return {
        "cause": _classify_delta(_arg_name(i, names), best[i], sig[i]),
        "arg": _arg_name(i, names),
        "old": best[i],
        "new": sig[i],
        "changed": [_arg_name(j, names) for j in best_diffs],
    }


def _jsonable(value: Any) -> Any:
    """Signature elements as JSONL-safe values (tuples render as their
    repr — a grammar signature is an opaque key, not data)."""
    if isinstance(value, (int, float, str, bool)) or value is None:
        return value
    return repr(value)


class CompileObserver:
    """Process-wide compile recorder; one instance per enabled process
    (module surface below).  All mutation goes through the counter
    registry, so snapshots/deltas/exposition ride the established
    machinery for free."""

    def __init__(self, events_path: Optional[str] = None):
        self._local = threading.local()
        self._lock = threading.Lock()
        self._cache_entries = 0
        self._retraces = 0
        self._cause_records = 0
        self._causes: deque = deque(maxlen=CAUSE_RING)
        self._sink = None
        # Register the namespace at construction: an enabled-but-idle
        # process still advertises the accounting surface (and the
        # exact-bytes zero-surface test has a definite complement).
        obs_counters.counter("engine.compile_obs.first_compile_ms")
        obs_counters.counter("engine.compile_obs.retrace_ms")
        obs_counters.counter("engine.compile_obs.aot_ms")
        obs_counters.gauge("engine.compile_obs.cache_entries")
        if events_path:
            from bcg_tpu.obs import export as obs_export

            self._sink = obs_export.EventSink(
                events_path,
                drop_counter="engine.compile_obs.events_dropped",
                manifest=obs_export.run_manifest(kind="compile"),
            )

    # ------------------------------------------------------------ recording

    def _pending(self) -> Dict[str, str]:
        pend = getattr(self._local, "pending", None)
        if pend is None:
            pend = self._local.pending = {}
        return pend

    def _stash(self) -> Dict[str, float]:
        stash = getattr(self._local, "stash", None)
        if stash is None:
            stash = self._local.stash = {}
        return stash

    def note_signature(
        self, entry: str, sig: Tuple, prior: Sequence[Tuple],
        names: Optional[Sequence[str]] = None,
        timing: str = "pending",
    ) -> None:
        """Record one trace-cache miss: ``sig`` is NEW for ``entry``
        (the caller's cache already established that), ``prior`` are the
        entry's earlier signatures in insertion order.  First signature
        per entry = first compile; later ones = retraces, each emitting
        exactly one structured cause record.

        ``timing`` declares the seam's note/dispatch ordering, which is
        a property of the CALL SITE, never inferred: ``"stash"`` = the
        note follows its timed dispatch on the same thread (prefill),
        so the block's just-written stash IS this miss's duration;
        ``"pending"`` = the note precedes the first invocation (the
        decode-loop builders), so a pending marker is left for the next
        block's exit — and any stale stash from an earlier STEADY-STATE
        dispatch of this entry is DISCARDED, not consumed (consuming it
        recorded the previous warm call's execute time as the retrace's
        compile time)."""
        first = not prior
        kind = "first_compile" if first else "retrace"
        with self._lock:
            self._cache_entries += 1
            entries = self._cache_entries
        obs_counters.set_gauge("engine.compile_obs.cache_entries", entries)
        if not first:
            self._record_cause(entry, sig, prior, names)
        stash = self._stash()
        elapsed = stash.pop(entry, None)
        if timing == "stash" and elapsed is not None:
            self._record_time(entry, kind, elapsed)
        else:
            # "pending" mode reaches here with any stale steady-state
            # elapsed already popped and dropped; a "stash" seam with
            # nothing stashed (a dispatch path that skipped its
            # time_block) degrades to the pending handoff rather than
            # losing the miss.
            self._pending()[entry] = kind
        self.publish()

    def _record_cause(
        self, entry: str, sig: Tuple, prior: Sequence[Tuple],
        names: Optional[Sequence[str]],
    ) -> None:
        cause = diff_signature(sig, prior, names=names)
        span = obs_tracer.current()
        attr = (
            _sanitize(span.name) if span is not None
            else f"jit_{_sanitize(entry)}"
        )
        with self._lock:
            self._retraces += 1
            self._cause_records += 1
            record = {
                "entry": entry,
                "cause": cause["cause"],
                "arg": cause["arg"],
                "old": _jsonable(cause["old"]),
                "new": _jsonable(cause["new"]),
                "changed": cause["changed"],
                "span": attr,
            }
            self._causes.append(record)
        obs_counters.inc(f"engine.retrace_cause.{cause['cause']}")
        if self._sink is not None:
            self._sink.emit("retrace_cause", **record)

    def time_block(self, entry: str) -> "_TimeBlock":
        return _TimeBlock(self, entry)

    def _block_exit(self, entry: str, seconds: float) -> None:
        kind = self._pending().pop(entry, None)
        if kind is not None:
            self._record_time(entry, kind, seconds)
            self.publish()
        else:
            # Steady-state call: keep the elapsed around for a seam
            # that notes AFTER its dispatch (prefill); overwritten per
            # call, consumed at most once.
            self._stash()[entry] = seconds

    def _record_time(self, entry: str, kind: str, seconds: float) -> None:
        ms = seconds * 1e3
        obs_counters.histogram(
            f"engine.compile_ms.{entry}", COMPILE_MS_BOUNDS
        ).observe(ms)
        if kind == "retrace":
            obs_counters.inc("engine.compile_obs.retrace_ms", ms)
        else:
            obs_counters.inc("engine.compile_obs.first_compile_ms", ms)

    def measure_aot(self, entry: str) -> "_AotBlock":
        return _AotBlock(self, entry)

    def _aot_exit(self, entry: str, seconds: float) -> None:
        # Own histogram name, never the serving entry's: the census AOT
        # runs INSIDE the entry's first dispatch (obs_hlo.wrap precedes
        # the jitted call), so observing it under the same name would
        # double-count the duration the enclosing time_block already
        # measures and inflate the entry's compile count.
        ms = seconds * 1e3
        obs_counters.histogram(
            f"engine.compile_ms.aot_{entry}", COMPILE_MS_BOUNDS
        ).observe(ms)
        obs_counters.inc("engine.compile_obs.aot_ms", ms)
        self.publish()

    # ------------------------------------------------------------- reading

    def cause_records(self) -> List[Dict[str, Any]]:
        """Copies of the retained cause records, oldest first (bounded
        by :data:`CAUSE_RING`; the JSONL sink carries the full
        stream)."""
        with self._lock:
            return [dict(r) for r in self._causes]

    def brief(self, snap: Optional[Dict] = None) -> Dict[str, Any]:
        """The serve-snapshot block: cache population, retrace/cause
        totals, cumulative compile milliseconds by kind.  ``snap``
        lets summary() reuse its own registry snapshot instead of
        paying a second full scan per trace-cache miss."""
        if snap is None:
            snap = obs_counters.snapshot()
        causes = {
            name[len("engine.retrace_cause."):]: int(value)
            for name, value in snap.items()
            if name.startswith("engine.retrace_cause.")
        }
        with self._lock:
            entries = self._cache_entries
            retraces = self._retraces
        return {
            "cache_entries": entries,
            "retraces": retraces,
            "causes": causes,
            "first_compile_ms": round(
                float(snap.get("engine.compile_obs.first_compile_ms", 0)), 3
            ),
            "retrace_ms": round(
                float(snap.get("engine.compile_obs.retrace_ms", 0)), 3
            ),
            "aot_ms": round(
                float(snap.get("engine.compile_obs.aot_ms", 0)), 3
            ),
        }

    def summary(self) -> Dict[str, Any]:
        """The bench-JSON / LAST_COMPILE_OBS form: the brief totals plus
        the per-entry compile-time table (count / total ms, rebuilt from
        the ``engine.compile_ms.<entry>`` histogram flats) and the
        retained cause records.  ONE registry snapshot feeds
        everything — publish() runs per miss, so it must not rescan the
        registry per table."""
        snap = obs_counters.snapshot()
        by_entry: Dict[str, Dict[str, float]] = {}
        for name, value in snap.items():
            if not name.startswith("engine.compile_ms."):
                continue
            rest = name[len("engine.compile_ms."):]
            if rest.endswith(".count"):
                entry = rest[: -len(".count")]
                by_entry.setdefault(entry, {})["count"] = int(value)
            elif rest.endswith(".sum"):
                entry = rest[: -len(".sum")]
                by_entry.setdefault(entry, {})["total_ms"] = round(
                    float(value), 3
                )
        out = self.brief(snap)
        out["compile_ms_by_entry"] = dict(sorted(by_entry.items()))
        out["recent_causes"] = self.cause_records()
        return out

    def publish(self) -> None:
        """Mirror the summary into ``runtime.metrics.LAST_COMPILE_OBS``
        so bench.py attaches it on success AND error paths (the
        LAST_SERVE_STATS idiom)."""
        from bcg_tpu.runtime import metrics

        metrics.publish_compile_obs(self.summary())

    def close(self) -> None:
        if self._sink is not None:
            self._sink.close()
            self._sink = None


class _TimeBlock:
    """Times one compile-triggering dispatch (see module docstring)."""

    __slots__ = ("_observer", "_entry", "_t0")

    def __init__(self, observer: CompileObserver, entry: str):
        self._observer = observer
        self._entry = entry

    def __enter__(self):
        self._t0 = time.perf_counter()
        return None

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self._observer._block_exit(
                self._entry, time.perf_counter() - self._t0
            )
        else:
            # A failed dispatch's partial duration is not a compile
            # measurement, but its pending marker MUST come off or the
            # next successful call of this entry records a wrong kind.
            self._observer._pending().pop(self._entry, None)
        return False


class _AotBlock:
    """Times the HLO census's AOT lower+compile for one entry."""

    __slots__ = ("_observer", "_entry", "_t0")

    def __init__(self, observer: CompileObserver, entry: str):
        self._observer = observer
        self._entry = entry

    def __enter__(self):
        self._t0 = time.perf_counter()
        return None

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self._observer._aot_exit(
                self._entry, time.perf_counter() - self._t0
            )
        return False


# ---------------------------------------------------------- module surface
_config_lock = threading.Lock()
_observer: Optional[CompileObserver] = None
_configured = False

_TRUTHY = ("1", "true", "yes", "on")


def _parse_flag(raw: Optional[str]) -> Tuple[bool, Optional[str]]:
    """``BCG_TPU_COMPILE_OBS`` dual-mode parse: falsy/unset = off;
    a plain truthy token = counters only; anything else = counters plus
    the retrace-cause JSONL stream at that path (the BCG_TPU_XLA_CACHE
    value-or-path idiom)."""
    if raw is None:
        return False, None
    token = raw.strip()
    if not token or token.lower() in ("0", "false", "no", "off"):
        return False, None
    if token.lower() in _TRUTHY:
        return True, None
    return True, token


def _ensure() -> Optional[CompileObserver]:
    global _observer, _configured
    if _configured:
        return _observer
    with _config_lock:
        if not _configured:
            on, path = _parse_flag(
                envflags.get_str("BCG_TPU_COMPILE_OBS")
            )
            if on:
                _observer = CompileObserver(events_path=path)
            _configured = True
    return _observer


def observer() -> Optional[CompileObserver]:
    """The active observer, or None when compile observability is
    disabled."""
    return _ensure()


def enabled() -> bool:
    return _ensure() is not None


def note_signature(entry: str, sig: Tuple, prior: Sequence[Tuple],
                   names: Optional[Sequence[str]] = None,
                   timing: str = "pending") -> None:
    """Record one trace-cache miss (module-level seam API; no-op when
    disabled — call sites never need their own guard)."""
    o = _observer if _configured else _ensure()
    if o is not None:
        o.note_signature(entry, sig, prior, names=names, timing=timing)


def time_block(entry: str):
    """Context manager timing a compile-triggering dispatch; shared
    no-op when disabled."""
    o = _observer if _configured else _ensure()
    return o.time_block(entry) if o is not None else _NULL_CM


def measure_aot(entry: str):
    """Context manager timing the HLO census's AOT lower+compile;
    shared no-op when disabled."""
    o = _observer if _configured else _ensure()
    return o.measure_aot(entry) if o is not None else _NULL_CM


def brief() -> Optional[Dict[str, Any]]:
    o = _observer if _configured else _ensure()
    return o.brief() if o is not None else None


def summary() -> Optional[Dict[str, Any]]:
    o = _observer if _configured else _ensure()
    return o.summary() if o is not None else None


def cause_records() -> List[Dict[str, Any]]:
    o = _observer if _configured else _ensure()
    return o.cause_records() if o is not None else []


def publish() -> None:
    o = _observer if _configured else _ensure()
    if o is not None:
        o.publish()


def reset() -> None:
    """Drop the cached observer + read-once flag caches (including the
    profiler window state) so the next use re-reads the environment —
    TEST-ONLY.  Registered ``engine.compile_obs.*`` counters persist in
    the registry (live consumers hold baselines); tests needing a
    pristine registry use a subprocess (the zero-surface pin)."""
    global _observer, _configured, _profile, _profile_configured
    global _dispatch_seq
    with _config_lock:
        if _observer is not None:
            _observer.close()
        _observer = None
        _configured = False
    with _profile_lock:
        if _profile is not None and _profile.get("active"):
            _stop_profiler(_profile)
        _profile = None
        _profile_configured = False
        _dispatch_seq = 0


# ------------------------------------------------------- profiler windows
_profile_lock = threading.Lock()
_profile: Optional[Dict[str, Any]] = None
_profile_configured = False
_dispatch_seq = 0

_ROUNDS_RE = re.compile(r"^\s*(\d+)\s*(?:-\s*(\d+)\s*)?$")


def _parse_rounds(raw: Optional[str]) -> Tuple[int, int]:
    """``a-b`` (or a bare ``a`` = one round) -> inclusive window; an
    unparseable value warns LOUDLY and falls back to the registered
    default — silently profiling the wrong rounds would be worse than
    either crashing or defaulting (the envflags.get_int contract)."""
    m = _ROUNDS_RE.match(raw or "")
    if m is None:
        import sys

        print(
            f"obs.compile: BCG_TPU_PROFILE_ROUNDS={raw!r} is not 'a-b' — "
            "using 1-2",
            file=sys.stderr,
        )
        return 1, 2
    lo = int(m.group(1))
    hi = int(m.group(2)) if m.group(2) is not None else lo
    return (lo, hi) if hi >= lo else (hi, lo)


def _profile_cfg() -> Optional[Dict[str, Any]]:
    """Read-once profiler-window config, or None when capture is off."""
    global _profile, _profile_configured
    if _profile_configured:
        return _profile
    with _profile_lock:
        if not _profile_configured:
            log_dir = envflags.get_str("BCG_TPU_PROFILE")
            if log_dir:
                lo, hi = _parse_rounds(
                    envflags.get_str("BCG_TPU_PROFILE_ROUNDS")
                )
                _profile = {
                    "dir": log_dir, "lo": lo, "hi": hi,
                    "active": False, "done": False, "owner": None,
                }
            _profile_configured = True
    return _profile


def _start_profiler(state: Dict[str, Any], kind: str) -> bool:
    """Start the jax profiler + write the window manifest; a failure
    marks the window done (warn once, never take the round down)."""
    import atexit
    import json
    import os

    try:
        import jax

        os.makedirs(state["dir"], exist_ok=True)
        from bcg_tpu.obs import export as obs_export

        with open(os.path.join(state["dir"], "manifest.json"), "w") as f:
            json.dump(
                obs_export.run_manifest(
                    kind="profile", window_kind=kind,
                    first_index=state["lo"], last_index=state["hi"],
                ),
                f, indent=2, default=str,
            )
        jax.profiler.start_trace(state["dir"])
        atexit.register(_atexit_stop)
        return True
    except (ImportError, OSError, RuntimeError, ValueError) as exc:
        import sys

        print(
            f"obs.compile: profiler window failed to start "
            f"({state['dir']}): {exc} — capture disabled",
            file=sys.stderr,
        )
        state["done"] = True
        return False


def _stop_profiler(state: Dict[str, Any]) -> None:
    try:
        import jax

        jax.profiler.stop_trace()
    except (ImportError, RuntimeError, ValueError):
        pass
    state["active"] = False
    state["done"] = True


def _atexit_stop() -> None:
    """A run shorter than the window must not leave the profiler
    recording into a torn trace at interpreter exit."""
    with _profile_lock:
        if _profile is not None and _profile.get("active"):
            _stop_profiler(_profile)


class _ProfileCm:
    """One round/dispatch inside the capture window: starts the
    profiler when its index reaches the window floor (first stream to
    arrive owns the window), stops it after the owning stream passes
    the ceiling."""

    __slots__ = ("_kind", "_index")

    def __init__(self, kind: str, index: int):
        self._kind = kind
        self._index = index

    def __enter__(self):
        state = _profile_cfg()
        if state is None:  # reset() raced the window away
            return None
        with _profile_lock:
            if (not state["active"] and not state["done"]
                    and state["lo"] <= self._index <= state["hi"]):
                if _start_profiler(state, self._kind):
                    state["active"] = True
                    state["owner"] = self._kind
        return None

    def __exit__(self, exc_type, exc, tb):
        state = _profile_cfg()
        if state is None:
            return False
        with _profile_lock:
            if (state["active"] and state["owner"] == self._kind
                    and self._index >= state["hi"]):
                _stop_profiler(state)
        return False


def profile_span(kind: str, index: int):
    """Context manager bounding one candidate capture unit (an
    orchestrator round, a serve dispatch) at 1-based ``index``; shared
    no-op when capture is off or the window already closed."""
    state = _profile_cfg()
    if state is None or state["done"]:
        return _NULL_CM
    return _ProfileCm(kind, index)


def profile_dispatch():
    """The serve-dispatch form of :func:`profile_span`: dispatches are
    numbered process-wide in dispatch order (the scheduler has no round
    numbers), so ``BCG_TPU_PROFILE_ROUNDS=a-b`` captures dispatches
    ``a..b``."""
    global _dispatch_seq
    state = _profile_cfg()
    if state is None or state["done"]:
        return _NULL_CM
    with _profile_lock:
        _dispatch_seq += 1
        index = _dispatch_seq
    return _ProfileCm("dispatch", index)
