"""Runtime host↔device transfer auditor (``BCG_TPU_HOSTSYNC``).

ROADMAP item 1 ("on-device mega-round") names its target metric —
*host-syncs per round → ~1* — but until this module nothing at runtime
COUNTED the device→host round-trips the game loop actually performs:
``BCG-HOST-SYNC`` is a static AST rule over traced regions, blind to
the eager seams (decode readback, ``block_until_ready`` barriers,
``np.asarray`` coercions, the guided parse) where the real per-decision
cost lives.  This auditor closes the gap the way the while-body kernel
census (obs/hlo.py) closed it for kernel counts: observe, attribute,
drift-gate.

Mechanics — two complementary capture paths:

* **Instrumented seams.**  The known materialization points call
  :func:`note` with a site name and the active jit-entry name:
  ``engine/jax_engine.py``'s decode path (prefill barrier, decode-loop
  output readback, step-count readback, speculative draft/accept
  readback) and the FakeEngine's hermetic mirror of the same profile
  (the ``engine.spec.*`` mirror idiom: hermetic games carry the real
  loop's sync structure so the gate can pin calls-per-round without a
  device).  Python cannot intercept ``.block_until_ready()`` or
  ``np.asarray`` centrally (C-level methods on ``jax.Array``), so the
  seams are explicit — which is also what makes each one attributable.
* **``jax.transfer_guard("log")``-style interception.**  When the
  auditor is on, the public ``jax.device_get`` entry point is wrapped
  so untagged materializations through it are still counted (site
  ``device_get``) instead of escaping the audit.  :func:`reset`
  uninstalls the wrapper.

Attribution, per observed sync (acceptance: ≥95% attributed in the
hermetic scenario; the remainder is COUNTED as unattributed, never
dropped):

1. the innermost open tracer span on the calling thread
   (:func:`bcg_tpu.obs.tracer.current` — PR 4's thread-local parent
   machinery), when tracing is on;
2. else the jit-entry name — the explicit ``entry=`` tag a seam
   passes, or the top of the thread-local :func:`jit_entry` stack —
   rendered as ``jit_<entry>`` so the table distinguishes the two;
3. else ``unattributed``.

Surfaces (all zero when the flag is off — no counters registered, no
interception installed, Prometheus exposition and tracer export
byte-identical to an unaudited process; tests/test_hostsync.py pins
the exposition bytes):

* ``engine.hostsync.total`` / ``.attributed`` / ``.unattributed``
  counters, plus ``engine.hostsync.site.<site>`` per seam and the
  attribution table ``engine.hostsync.span.<name>`` — which rides the
  tracer export's embedded counters, so ``scripts/trace_report.py``
  renders "host syncs by span" offline;
* the ``game.host_syncs`` per-round histogram, observed by the
  orchestrator around each ``round`` span;
* the serve ``SchedulerStats`` snapshot's ``hostsync`` block
  (per-dispatch / per-request sync counts);
* ``runtime.metrics.LAST_HOSTSYNC`` (:func:`publish`), so ``bench.py``
  attaches the profile on success AND error paths;
* the ``hostsync`` perf_gate scenario (scripts/perf_gate.py), pinning
  syncs-per-round (hermetic FakeEngine game) and syncs-per-decision
  (tiny real engine) in ``perf_baseline.json`` — the baseline every
  item-2 fusion PR must justify moving, exactly like the while-body
  census did for PRs 8/10.

Flags are read ONCE at first use (per-note env reads would be
measurable on the decode hot path); tests reconfigure via
:func:`reset`.  No jax import at module scope — loadable by flag-only
consumers (bench.py's error path); jax is touched only inside
interception install/uninstall, and only when the auditor is enabled.
"""

from __future__ import annotations

import re
import threading
from typing import Dict, Optional

from bcg_tpu.obs import counters as obs_counters
from bcg_tpu.obs import tracer as obs_tracer
from bcg_tpu.runtime import envflags

# Attribution/site fragments must stay inside the metric-name taxonomy
# ([a-z0-9_] per segment, BCG-OBS-NAME): span names like
# ``serve.request`` flatten to ``serve_request``.
_SANITIZE_RE = re.compile(r"[^a-z0-9_]")

# Per-round sync histogram bounds.  Today's lockstep round performs a
# handful of syncs per batched engine call; the mega-round target is ~1,
# so the ladder resolves both the current regime and the fused one.
ROUND_SYNC_BOUNDS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0,
                     512.0)


def _sanitize(name: str) -> str:
    return _SANITIZE_RE.sub("_", name.lower())


class _NullEntry:
    """Shared no-op context manager — the disabled-auditor fast path
    (the tracer's ``_NullSpan`` idiom)."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL_ENTRY = _NullEntry()


class _EntryCm:
    """Pushes one jit-entry name onto the calling thread's stack for the
    duration of the block — the tracing-off attribution source."""

    __slots__ = ("_auditor", "_name")

    def __init__(self, auditor: "HostSyncAuditor", name: str):
        self._auditor = auditor
        self._name = name

    def __enter__(self):
        self._auditor._entry_stack().append(self._name)
        return None

    def __exit__(self, exc_type, exc, tb):
        stack = self._auditor._entry_stack()
        if stack:
            stack.pop()
        return False


class _RoundWindow:
    """One open game round's audit window: the auditor total at round
    start, plus whether another round overlapped it (concurrent games —
    see :meth:`HostSyncAuditor.end_round`)."""

    __slots__ = ("start", "overlapped")

    def __init__(self, start: int):
        self.start = start
        self.overlapped = False


class HostSyncAuditor:
    """Process-wide sync recorder; one instance per enabled process
    (module surface below).  All mutation goes through the counter
    registry, so snapshots/deltas/exposition ride the established
    machinery for free."""

    def __init__(self):
        self._local = threading.local()
        self._installed_device_get = None
        self._orig_device_get = None
        # install/uninstall_interception run from serve workers, the
        # scheduler thread, and sweep workers alike; the check-then-act
        # on _installed_device_get must be atomic or two installers can
        # chain-wrap jax.device_get and lose the true original.
        self._install_lock = threading.Lock()
        self._round_lock = threading.Lock()
        self._open_rounds: list = []
        # Register the namespace at construction: an enabled-but-idle
        # process still advertises the audit surface (and the exact-
        # bytes zero-surface test has a definite complement to pin).
        obs_counters.counter("engine.hostsync.total")
        obs_counters.counter("engine.hostsync.attributed")
        obs_counters.counter("engine.hostsync.unattributed")

    # ------------------------------------------------------------ recording

    def _entry_stack(self) -> list:
        stack = getattr(self._local, "entries", None)
        if stack is None:
            stack = self._local.entries = []
        return stack

    def jit_entry(self, name: str) -> _EntryCm:
        return _EntryCm(self, name)

    def current_entry(self) -> Optional[str]:
        stack = getattr(self._local, "entries", None)
        return stack[-1] if stack else None

    def note(self, site: str, n: int = 1, entry: Optional[str] = None) -> None:
        """Record ``n`` device→host materializations at ``site``,
        attributed span-first (innermost open tracer span), then to the
        jit-entry name (explicit ``entry=`` beats the thread-local
        stack), else counted unattributed."""
        if n <= 0:
            return
        span = obs_tracer.current()
        if span is not None:
            attr = _sanitize(span.name)
        else:
            jit = entry if entry is not None else self.current_entry()
            attr = f"jit_{_sanitize(jit)}" if jit else None
        obs_counters.inc("engine.hostsync.total", n)
        obs_counters.inc(f"engine.hostsync.site.{_sanitize(site)}", n)
        if attr is not None:
            obs_counters.inc("engine.hostsync.attributed", n)
            obs_counters.inc(f"engine.hostsync.span.{attr}", n)
        else:
            obs_counters.inc("engine.hostsync.unattributed", n)
            obs_counters.inc("engine.hostsync.span.unattributed", n)

    def total(self) -> int:
        return int(obs_counters.value("engine.hostsync.total"))

    def begin_round(self) -> _RoundWindow:
        """Open one game round's audit window.  Any other round open at
        the same time (concurrent games sharing one serving engine)
        marks BOTH windows overlapped: the process-wide total cannot
        split a shared dispatch batch's syncs between games, and an
        overcounted observation would corrupt exactly the metric the
        mega-round work drives down."""
        with self._round_lock:
            window = _RoundWindow(self.total())
            if self._open_rounds:
                window.overlapped = True
                for other in self._open_rounds:
                    other.overlapped = True
            self._open_rounds.append(window)
        return window

    def end_round(self, window: _RoundWindow, observe: bool = True) -> None:
        """Close a round window: an unoverlapped round observes its
        exact sync delta into the ``game.host_syncs`` histogram
        (created here — only an enabled auditor ever registers it);
        an overlapped one is COUNTED (``engine.hostsync.rounds_overlapped``)
        rather than observed wrong or dropped silently.

        ``observe=False`` discards the window without recording — the
        failed-round path, which must still REMOVE the window: a leaked
        entry in ``_open_rounds`` would mark every later round
        overlapped and silently stop the histogram for the rest of the
        process."""
        with self._round_lock:
            if window in self._open_rounds:
                self._open_rounds.remove(window)
            syncs = self.total() - window.start
            overlapped = window.overlapped
        if not observe:
            return
        if overlapped:
            obs_counters.inc("engine.hostsync.rounds_overlapped")
        else:
            obs_counters.histogram("game.host_syncs",
                                   ROUND_SYNC_BOUNDS).observe(syncs)
        self.publish()

    # -------------------------------------------------------- interception

    def install_interception(self) -> None:
        """Wrap the public ``jax.device_get`` so materializations that
        bypass the instrumented seams are still counted (site
        ``device_get``).  Failure to import jax degrades to seam-only
        auditing — bench.py's error path must stay loadable."""
        try:
            import jax
        except ImportError:
            return
        with self._install_lock:
            if self._installed_device_get is not None:
                return
            orig = jax.device_get

            def _audited_device_get(x):
                self.note("device_get")
                return orig(x)

            self._orig_device_get = orig
            self._installed_device_get = _audited_device_get
            jax.device_get = _audited_device_get

    def uninstall_interception(self) -> None:
        with self._install_lock:
            if self._installed_device_get is None:
                return
            import jax

            # Only restore if nothing else re-wrapped it after us.
            if jax.device_get is self._installed_device_get:
                jax.device_get = self._orig_device_get
            self._installed_device_get = None
            self._orig_device_get = None

    # ------------------------------------------------------------- reading

    @staticmethod
    def _table(snap: Dict, prefix: str) -> Dict[str, int]:
        return {
            name[len(prefix):]: int(value)
            for name, value in snap.items()
            if name.startswith(prefix)
        }

    def attribution_table(self) -> Dict[str, int]:
        """{attribution name: syncs} — span names as recorded,
        jit-entry attributions under their ``jit_`` prefix, plus
        ``unattributed`` when anything escaped."""
        return self._table(obs_counters.snapshot(),
                           "engine.hostsync.span.")

    def site_table(self) -> Dict[str, int]:
        return self._table(obs_counters.snapshot(),
                           "engine.hostsync.site.")

    def summary(self) -> Dict:
        """The bench-JSON / LAST_HOSTSYNC form: totals, attribution
        coverage, per-site and per-attribution tables, and the
        per-round histogram's count/sum/mean when any round was
        observed.  ONE registry snapshot feeds everything — publish()
        runs this per generation call, so it must not rescan the
        registry per table."""
        snap = obs_counters.snapshot()
        total = int(snap.get("engine.hostsync.total", 0))
        attributed = int(snap.get("engine.hostsync.attributed", 0))
        out: Dict = {
            "total": total,
            "attributed": attributed,
            "unattributed": int(
                snap.get("engine.hostsync.unattributed", 0)
            ),
            "attribution_coverage": (
                round(attributed / total, 4) if total else None
            ),
            "by_site": self._table(snap, "engine.hostsync.site."),
            "by_span": self._table(snap, "engine.hostsync.span."),
        }
        rounds = int(snap.get("game.host_syncs.count", 0))
        if rounds:
            syncs = snap.get("game.host_syncs.sum", 0)
            out["rounds"] = {
                "count": rounds,
                "syncs": int(syncs),
                "syncs_per_round": round(syncs / rounds, 4),
            }
        return out

    def publish(self) -> None:
        """Mirror the summary into ``runtime.metrics.LAST_HOSTSYNC`` so
        bench.py attaches it on success AND error paths (the
        LAST_SERVE_STATS idiom: a mid-wave crash keeps the profile the
        completed calls already recorded)."""
        from bcg_tpu.runtime import metrics

        metrics.publish_hostsync(self.summary())


# ---------------------------------------------------------- module surface
_config_lock = threading.Lock()
_auditor: Optional[HostSyncAuditor] = None
_configured = False


def _ensure() -> Optional[HostSyncAuditor]:
    global _auditor, _configured
    if _configured:
        return _auditor
    with _config_lock:
        if not _configured:
            if envflags.get_bool("BCG_TPU_HOSTSYNC"):
                _auditor = HostSyncAuditor()
                _auditor.install_interception()
            _configured = True
    return _auditor


def auditor() -> Optional[HostSyncAuditor]:
    """The active auditor, or None when auditing is disabled."""
    return _ensure()


def enabled() -> bool:
    return _ensure() is not None


def note(site: str, n: int = 1, entry: Optional[str] = None) -> None:
    """Record ``n`` syncs at ``site`` (module-level seam API; no-op when
    disabled — call sites never need their own guard)."""
    a = _auditor if _configured else _ensure()
    if a is not None:
        a.note(site, n, entry=entry)


def jit_entry(name: str):
    """Context manager labelling the block with a jit-entry name for
    tracing-off attribution; shared no-op when disabled."""
    a = _auditor if _configured else _ensure()
    return a.jit_entry(name) if a is not None else _NULL_ENTRY


def total() -> int:
    a = _auditor if _configured else _ensure()
    return a.total() if a is not None else 0


def summary() -> Optional[Dict]:
    a = _auditor if _configured else _ensure()
    return a.summary() if a is not None else None


def publish() -> None:
    a = _auditor if _configured else _ensure()
    if a is not None:
        a.publish()


def reset() -> None:
    """Uninstall interception and drop the cached auditor + read-once
    flag cache so the next use re-reads the environment — TEST-ONLY.
    Registered ``engine.hostsync.*`` counters persist in the registry
    (live consumers hold baselines); tests needing a pristine registry
    use a subprocess (tests/test_hostsync.py zero-surface pin)."""
    global _auditor, _configured
    with _config_lock:
        if _auditor is not None:
            _auditor.uninstall_interception()
        _auditor = None
        _configured = False
