"""HLO kernel census: lowered-program introspection per jit entry.

ROADMAP item 5 names "kernel-count per decode step via lowered-HLO
inspection" as the acceptance instrument for any fusion work, and the
retrace counters only say *that* a program recompiled — not what it
compiled INTO.  This module closes that gap: when the census is enabled
(``BCG_TPU_HLO_CENSUS=1``, or programmatically via :func:`enable`),
``engine/jax_engine.py`` hands each jit entry point's FIRST call here
(:func:`maybe_record`), the already-traced arguments are lowered and
compiled once more through the AOT API, and the compiled module is
parsed into an op census:

* **kernel-launching computations only** — the entry computation plus
  everything reachable through ``body=``/``condition=``/
  ``branch_computations=`` references (a while body's ops run once per
  decode step).  Computations referenced via ``calls=`` (fusion
  internals) or ``to_apply=`` (reduction lambdas) are *inside* a kernel
  and excluded, so ``total_ops`` approximates dispatched kernels, not
  HLO instructions.
* **category counts** — fusions, custom-calls, collectives
  (all-reduce / all-gather / reduce-scatter / collective-permute /
  all-to-all), scatter/gather, dynamic-(update-)slice, dots, whiles;
  plus the same counts restricted to while BODIES (``step_ops`` etc. —
  the per-decode-step kernel count the ROADMAP wants pinned).
* **XLA cost analysis** — flops and bytes-accessed of the compiled
  module, when the backend exposes them.

Every census lands in the process-wide counter registry as gauges
(``engine.hlo.<entry>.<metric>``) so it rides bench JSON and the
Prometheus exposition for free, and in :data:`CENSUS` for structured
consumers (``scripts/hlo_census.py``, the drift check against
``hlo_baseline.json``).

Cost: one extra lower+compile per (entry, first call) — which is why
the census is OFF by default and meant for the hermetic CPU census
script and tier-1 drift test, not the serving hot path.  Recording
never raises: a backend without ``as_text``/``cost_analysis`` simply
yields a partial census.

jax is imported lazily inside :func:`maybe_record` so this module stays
loadable by flag-only consumers (the trace-report path).
"""

from __future__ import annotations

import re
import threading
from typing import Any, Dict, List, Optional, Tuple

from bcg_tpu.obs import counters as obs_counters
from bcg_tpu.runtime import envflags

_COLLECTIVES = {
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all", "collective-broadcast", "all-reduce-start",
    "all-gather-start",
}
# Census metric names, in render order.  ``flops``/``bytes_accessed``
# ride separately (cost analysis, not op parsing).
COUNT_METRICS = (
    "total_ops", "fusions", "custom_calls", "collectives", "scatters",
    "gathers", "dynamic_slices", "dots", "whiles",
    "step_ops", "step_fusions", "step_dots", "step_collectives",
    "step_gathers", "step_custom_calls",
)

_comp_header_re = re.compile(r"^(ENTRY\s+)?%([\w.\-]+)\s*\(.*\)\s*->.*\{$")
# The result type is either a scalar/array type (no spaces) or a tuple
# "(f32[...], s32[])" — a plain \S+ match would skip every tuple-typed
# instruction (the while op itself, multi-output fusions).
_op_re = re.compile(
    r"^\s*(?:ROOT\s+)?%[\w.\-]+\s*=\s*(?:\([^)]*\)|\S+)\s+([a-z][a-z0-9\-]*)\("
)
_ref_res = {
    "body": re.compile(r"body=%?([\w.\-]+)"),
    "condition": re.compile(r"condition=%?([\w.\-]+)"),
    "branch": re.compile(r"branch_computations=\{([^}]*)\}"),
}


def parse_computations(hlo_text: str) -> Tuple[Optional[str], Dict[str, List[str]]]:
    """(entry computation name, {computation: [opcode, ...]}) from HLO
    long-form text."""
    comps: Dict[str, List[str]] = {}
    entry = None
    cur: Optional[str] = None
    for line in hlo_text.splitlines():
        s = line.rstrip()
        m = _comp_header_re.match(s)
        if m:
            cur = m.group(2)
            comps[cur] = []
            if m.group(1):
                entry = cur
            continue
        if s == "}":
            cur = None
            continue
        if cur is None:
            continue
        m2 = _op_re.match(s)
        if m2:
            comps[cur].append(m2.group(1))
    return entry, comps


def _categorize(ops: List[str]) -> Dict[str, int]:
    return {
        "total_ops": len(ops),
        "fusions": sum(1 for o in ops if o == "fusion"),
        "custom_calls": sum(1 for o in ops if o == "custom-call"),
        "collectives": sum(1 for o in ops if o in _COLLECTIVES),
        "scatters": sum(1 for o in ops if o == "scatter"),
        "gathers": sum(1 for o in ops if o == "gather"),
        "dynamic_slices": sum(
            1 for o in ops if o in ("dynamic-slice", "dynamic-update-slice")
        ),
        "dots": sum(1 for o in ops if o in ("dot", "dot-general", "convolution")),
        "whiles": sum(1 for o in ops if o == "while"),
    }


def census_from_text(hlo_text: str) -> Dict[str, int]:
    """Op census over the KERNEL-LAUNCHING computations of one compiled
    module (see module docstring for the inclusion rule), with the
    ``step_*`` family restricted to while bodies."""
    entry, comps = parse_computations(hlo_text)
    body_names = set(_ref_res["body"].findall(hlo_text))
    cond_names = set(_ref_res["condition"].findall(hlo_text))
    branch_names = set()
    for group in _ref_res["branch"].findall(hlo_text):
        for name in group.split(","):
            branch_names.add(name.strip().lstrip("%"))
    launching = (
        ({entry} if entry else set()) | body_names | cond_names | branch_names
    )
    all_ops: List[str] = []
    step_ops: List[str] = []
    for name, ops in comps.items():
        if name not in launching:
            continue
        all_ops.extend(ops)
        if name in body_names:
            step_ops.extend(ops)
    census = _categorize(all_ops)
    census.update(_step_family(_categorize(step_ops)))
    return census


def _step_family(step: Dict[str, int]) -> Dict[str, int]:
    return {
        "step_ops": step["total_ops"],
        "step_fusions": step["fusions"],
        "step_dots": step["dots"],
        "step_collectives": step["collectives"],
        "step_gathers": step["gathers"],
        "step_custom_calls": step["custom_calls"],
    }


# --------------------------------------------------- stablehlo (TPU lowering)
# The compiled-HLO census above is post-fusion and backend-exact, but it
# can only be taken on the backend the process runs on.  The claims the
# Pallas paged-attention kernel makes are TPU claims — on CPU the kernel
# runs through the interpret-mode EMULATION, whose lowering machinery
# inflates op counts and proves nothing about the hardware program.
# jax can, however, cross-LOWER a traced program for the TPU platform on
# any host (Mosaic kernels serialize into ``tpu_custom_call`` at
# lowering time; only the final compile needs hardware), so the fused-
# vs-gather comparison is taken on the TPU StableHLO lowering instead:
# both arms carry the identical transformer skeleton, and the attention
# inner region is the only difference — N gather/reshape/softmax ops per
# layer per step versus ONE fused kernel custom-call.  Pre-fusion op
# counts are not kernel counts, but at the same IR level with the same
# skeleton the strict inequality (and the per-layer attention gathers
# and dots vanishing from the step body in favor of one custom call per
# layer) is exactly the fusion claim, hermetically.

_mlir_op_re = re.compile(r'(?:=\s*|^\s*)"?stablehlo\.([a-z_0-9]+)"?[\s("]')


def census_from_stablehlo(text: str) -> Dict[str, int]:
    """Op census over a StableHLO (MLIR) module, with the ``step_*``
    family counting ops nested inside ``stablehlo.while`` regions.
    ``constant``/``return`` lines are excluded (materialization noise at
    this IR level); ``fusions`` is structurally 0 — StableHLO is
    pre-fusion, which is why entries recorded this way pin the
    comparison-bearing counts (gathers, custom calls, dots, step totals)
    rather than claiming kernel counts."""
    all_ops: List[str] = []
    step_ops: List[str] = []
    depth = 0
    # Active while ops: [region base depth, regions-opened flag].  An op
    # is in a step body iff it sits deeper than the OUTERMOST active
    # while; a while is popped once its regions opened and closed back
    # to base (`} do {` nets zero braces, so depth only returns to base
    # at the real end).
    stack: List[List] = []
    for line in text.splitlines():
        m = _mlir_op_re.search(line)
        if m:
            op = m.group(1).replace("_", "-")
            if op not in ("constant", "return"):
                all_ops.append(op)
                if stack and depth > stack[0][0]:
                    step_ops.append(op)
                if op == "while":
                    stack.append([depth, False])
        depth += line.count("{") - line.count("}")
        for entry in stack:
            if depth > entry[0]:
                entry[1] = True
        while stack and stack[-1][1] and depth <= stack[-1][0]:
            stack.pop()
    census = _categorize(all_ops)
    census.update(_step_family(_categorize(step_ops)))
    return census


def _cost_analysis(compiled) -> Dict[str, float]:
    try:
        ca = compiled.cost_analysis()
    except (TypeError, ValueError, AttributeError, NotImplementedError,
            RuntimeError, IndexError):
        # Backend without cost analysis (some TPU/PJRT paths raise
        # XlaRuntimeError/Unimplemented here) — census stays partial.
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return {}
    out = {}
    if "flops" in ca:
        out["flops"] = float(ca["flops"])
    if "bytes accessed" in ca:
        out["bytes_accessed"] = float(ca["bytes accessed"])
    return out


# --------------------------------------------------------------- recorder
# entry name -> census dict (counts + flops/bytes + backend).
CENSUS: Dict[str, Dict[str, Any]] = {}
_lock = threading.Lock()
_enabled: Optional[bool] = None  # tri-state: None = read the env flag


def enabled() -> bool:
    global _enabled
    if _enabled is None:
        _enabled = envflags.get_bool("BCG_TPU_HLO_CENSUS")
    return _enabled


def enable(on: bool = True) -> None:
    """Programmatic switch (``scripts/hlo_census.py``, tests) — wins
    over the env flag until :func:`reset`."""
    global _enabled
    _enabled = on


def reset() -> None:
    """Drop recorded censuses AND the cached enable flag — test/script
    use."""
    global _enabled
    with _lock:
        CENSUS.clear()
        _enabled = None


def maybe_record(entry: str, jitted, args: tuple, kwargs: Optional[dict] = None) -> None:
    """Record the census for ``entry`` from a jitted callable and the
    concrete arguments of a call the engine is ABOUT to make (first call
    per entry only; no-op when the census is disabled).

    Uses the AOT path (``jitted.lower(*args).compile()``) so the parsed
    module is exactly what this backend executes for these shapes.  The
    extra compile is paid once per entry and only in census mode; the
    jit's own execution cache is untouched, so enabling the census
    changes no shapes and provokes no retraces.
    """
    if not enabled() or entry in CENSUS:
        return
    with _lock:
        if entry in CENSUS:  # raced
            return
        census: Dict[str, Any] = {}
        try:
            import jax

            # Compile-cost accounting (BCG_TPU_COMPILE_OBS): the AOT
            # lower+compile below is a REAL extra compile this process
            # pays for the census — charged under the entry's
            # engine.compile_ms histogram + the cumulative aot_ms
            # counter (obs/compile.py; shared no-op when off).
            from bcg_tpu.obs import compile as obs_compile

            with obs_compile.measure_aot(entry):
                lowered = jitted.lower(*args, **(kwargs or {}))
                compiled = lowered.compile()
            census.update(census_from_text(compiled.as_text()))
            census.update(_cost_analysis(compiled))
            census["backend"] = jax.default_backend()
        except Exception as exc:
            # A census failure must never take the serving call down;
            # the partial record names the failure for the script/test.
            census["error"] = f"{type(exc).__name__}: {str(exc)[:200]}"
        CENSUS[entry] = census
    publish_gauges(entry, census)


def recorded(entry: str) -> bool:
    """True once ``entry`` has a census (callers can skip building the
    arguments for a record that would be a no-op)."""
    return entry in CENSUS


def record_tpu_lowering(entry: str, jitted, args: tuple,
                        kwargs: Optional[dict] = None) -> None:
    """Record a census of ``jitted``'s TPU cross-lowering (StableHLO)
    WITHOUT executing or compiling it — no hardware needed, and safe
    for programs (like the non-interpret Pallas paged loop) that could
    not run on this host at all.  The engine uses this to pin the
    fused-kernel-vs-gather comparison hermetically; see the
    stablehlo-census comment above.  First record per entry wins; a
    failure is contained as an error record like :func:`maybe_record`."""
    if not enabled() or entry in CENSUS:
        return
    with _lock:
        if entry in CENSUS:  # raced
            return
        census: Dict[str, Any] = {}
        try:
            traced = jitted.trace(*args, **(kwargs or {}))
            lowered = traced.lower(lowering_platforms=("tpu",))
            census.update(census_from_stablehlo(lowered.as_text()))
            census["backend"] = "stablehlo-tpu"
        except Exception as exc:
            census["error"] = f"{type(exc).__name__}: {str(exc)[:200]}"
        CENSUS[entry] = census
    publish_gauges(entry, census)


def wrap(entry: str, jitted):
    """Call-site shim: returns ``jitted`` unchanged unless the census is
    enabled and ``entry`` is still unrecorded, in which case the first
    call records the census (from the exact concrete arguments) before
    executing — so engine call sites pay ZERO overhead disabled and one
    AOT lower+compile per entry enabled."""
    if not enabled() or entry in CENSUS:
        return jitted

    def _recording_call(*args, **kwargs):
        maybe_record(entry, jitted, args, kwargs)
        return jitted(*args, **kwargs)

    return _recording_call


def publish_gauges(entry: str, census: Dict[str, Any]) -> None:
    """Mirror one census into registry gauges
    (``engine.hlo.<entry>.<metric>``) — the bench-JSON / Prometheus
    surface."""
    for metric in COUNT_METRICS + ("flops", "bytes_accessed"):
        value = census.get(metric)
        if value is not None:
            obs_counters.set_gauge(f"engine.hlo.{entry}.{metric}", value)


def snapshot() -> Dict[str, Dict[str, Any]]:
    """Copy of every recorded census (entry -> metrics)."""
    with _lock:
        return {k: dict(v) for k, v in CENSUS.items()}
