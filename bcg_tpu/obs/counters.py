"""Process-wide counter/gauge/histogram registry.

One :data:`REGISTRY` per process, holding named monotonic
:class:`Counter`\\ s, settable :class:`Gauge`\\ s, and fixed-bucket
:class:`Histogram`\\ s.  Layers increment into it directly (the serve
scheduler's latency histograms, the engine's compile/retrace
accounting); consumers read it three ways:

* ``snapshot()`` — flat ``{name: value}`` dict of every counter and
  gauge, the form ``bench.py`` attaches to its JSON (success AND error).
  Histograms flatten to ``<name>.count`` / ``<name>.sum`` plus
  CUMULATIVE ``<name>.bucket.le_<bound>`` entries, so one flat dict
  (and the tracer export that embeds it) carries the full distribution;
* ``delta(before)`` — counter movement since an earlier ``snapshot()``,
  the form tests assert on ("this scripted run incremented
  ``engine.retrace.decode_loop`` by exactly 1").  Histogram count and
  bucket entries participate (they are monotonic); ``.sum`` does not —
  a signed-observation histogram (SLO headroom) can move it downward;
* per-instance baselines — a consumer that needs *its own* share of a
  process-wide counter (e.g. one scheduler's latency histograms while
  another may have run earlier in the process) records ``value(name)``
  (or ``Histogram.raw()``) at construction and subtracts at read time.

Counters are strictly monotonic (``inc`` rejects negative amounts):
a counter that can go down is a gauge, and mixing the two breaks
``delta()``'s "movement since" semantics.  Histograms are declared with
their bucket bounds at first use (``histogram(name, bounds)``) and
observed with ``observe()``; bucket-derived quantiles use the
Prometheus ``histogram_quantile`` idiom (linear interpolation within
the bucket, the highest finite bound for the overflow bucket), so p99
precision is set by the declared bounds, not sample storage — a
histogram costs O(buckets) memory forever, never O(observations).
No jax import — this module must stay loadable by flag-only consumers
(bench.py's error path).
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Union

Number = Union[int, float]


class Counter:
    """Named monotonic counter."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value: Number = 0
        self._lock = threading.Lock()

    def inc(self, n: Number = 1) -> None:
        if n < 0:
            raise ValueError(
                f"counter {self.name!r}: inc({n}) — counters are "
                "monotonic; use a gauge for values that go down"
            )
        with self._lock:
            self._value += n

    @property
    def value(self) -> Number:
        return self._value


class Gauge:
    """Named point-in-time value (last set wins)."""

    __slots__ = ("name", "_value")

    def __init__(self, name: str):
        self.name = name
        self._value: Number = 0

    def set(self, value: Number) -> None:
        self._value = value

    @property
    def value(self) -> Number:
        return self._value


def bound_label(bound: float) -> str:
    """Bucket bound -> flat-name fragment (``25`` -> ``le_25``'s ``25``,
    ``2.5`` -> ``2_5``): integers render bare, non-integers replace the
    decimal point so the fragment stays inside the metric-name taxonomy
    (``[a-z0-9_]``).  Bounds are validated non-negative at histogram
    construction, so no sign marker is ever needed."""
    if float(bound) == int(bound):
        return str(int(bound))
    return repr(float(bound)).replace(".", "_")


def quantile_from_counts(
    bounds: Sequence[float], counts: Sequence[Number], q: float
) -> float:
    """Bucket-derived quantile over NON-cumulative per-bucket ``counts``
    (len = len(bounds) + 1; the last entry is the overflow bucket).

    Prometheus ``histogram_quantile`` semantics: linear interpolation
    within the bucket the target rank falls into (lower edge 0 for the
    first bucket), and the highest FINITE bound when the rank lands in
    the overflow bucket — a quantile can never exceed what the declared
    bounds can resolve."""
    total = sum(counts)
    if total <= 0:
        return 0.0
    target = q * total
    prev_bound = 0.0
    cum = 0.0
    for bound, count in zip(bounds, counts):
        cum += count
        if cum >= target and count > 0:
            frac = (target - (cum - count)) / count
            return prev_bound + (float(bound) - prev_bound) * max(0.0, min(1.0, frac))
        prev_bound = float(bound)
    return float(bounds[-1])


class Histogram:
    """Named fixed-bucket histogram: ``observe()`` assigns each value to
    the first bucket whose upper bound admits it (values past the last
    bound land in the implicit overflow/+Inf bucket).  Bounds are fixed
    at construction — quantiles derive from bucket counts, so two
    histograms are mergeable and exposition is O(buckets)."""

    __slots__ = ("name", "bounds", "_counts", "_sum", "_count", "_lock")

    def __init__(self, name: str, bounds: Iterable[float]):
        self.name = name
        self.bounds: Tuple[float, ...] = tuple(float(b) for b in bounds)
        if not self.bounds:
            raise ValueError(f"histogram {name!r}: needs at least one bucket bound")
        if any(not math.isfinite(b) for b in self.bounds):
            raise ValueError(f"histogram {name!r}: bounds must be finite "
                             "(the +Inf bucket is implicit)")
        if any(b < 0 for b in self.bounds):
            raise ValueError(f"histogram {name!r}: bounds must be non-negative "
                             "(negative observations land in the first bucket)")
        if list(self.bounds) != sorted(set(self.bounds)):
            raise ValueError(f"histogram {name!r}: bounds must be strictly "
                             f"ascending, got {self.bounds}")
        self._counts: List[int] = [0] * (len(self.bounds) + 1)
        self._sum: float = 0.0
        self._count: int = 0
        self._lock = threading.Lock()

    def observe(self, value: Number) -> None:
        v = float(value)
        idx = len(self.bounds)  # overflow unless a bound admits it
        for i, bound in enumerate(self.bounds):
            if v <= bound:
                idx = i
                break
        with self._lock:
            self._counts[idx] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def raw(self) -> Tuple[List[int], float, int]:
        """``(per-bucket counts incl. overflow, sum, count)`` — the
        per-instance-baseline form (a consumer snapshots this at
        construction and subtracts at read time)."""
        with self._lock:
            return list(self._counts), self._sum, self._count

    def cumulative(self) -> List[Tuple[float, int]]:
        """``[(bound, cumulative_count), ...]`` over the finite bounds
        (Prometheus ``_bucket{le=...}`` semantics; the +Inf bucket equals
        ``count``)."""
        with self._lock:
            counts = list(self._counts)
        out = []
        cum = 0
        for bound, c in zip(self.bounds, counts):
            cum += c
            out.append((bound, cum))
        return out

    def quantile(self, q: float) -> float:
        counts, _, _ = self.raw()
        return quantile_from_counts(self.bounds, counts, q)

    def quantiles(self, qs: Sequence[float] = (0.5, 0.95, 0.99)) -> Dict[str, float]:
        """``{"p50": ..., "p95": ..., "p99": ...}`` (keys derived from
        ``qs``), each bucket-interpolated."""
        counts, _, _ = self.raw()
        return {
            f"p{int(round(q * 100))}": quantile_from_counts(self.bounds, counts, q)
            for q in qs
        }

    def flat(self) -> Dict[str, Number]:
        """Flat snapshot entries: ``<name>.count`` / ``<name>.sum`` /
        cumulative ``<name>.bucket.le_<bound>`` (the +Inf bucket is
        elided — it always equals ``.count``)."""
        with self._lock:
            counts = list(self._counts)
            total, n = self._sum, self._count
        out: Dict[str, Number] = {}
        cum = 0
        for bound, c in zip(self.bounds, counts):
            cum += c
            out[f"{self.name}.bucket.le_{bound_label(bound)}"] = cum
        out[f"{self.name}.sum"] = total
        out[f"{self.name}.count"] = n
        return out


class Registry:
    """Name -> Counter/Gauge/Histogram map; create-on-first-use
    accessors (histograms additionally need bounds at creation)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            if name in self._gauges:
                raise TypeError(f"{name!r} is registered as a gauge")
            if name in self._histograms:
                raise TypeError(f"{name!r} is registered as a histogram")
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            if name in self._counters:
                raise TypeError(f"{name!r} is registered as a counter")
            if name in self._histograms:
                raise TypeError(f"{name!r} is registered as a histogram")
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name)
            return g

    def histogram(self, name: str,
                  bounds: Optional[Iterable[float]] = None) -> Histogram:
        """The named histogram, created with ``bounds`` on first use.
        Later accessors may omit bounds (read access) or repeat the SAME
        bounds; conflicting bounds raise — two call sites disagreeing on
        buckets would silently merge incompatible distributions."""
        with self._lock:
            if name in self._counters:
                raise TypeError(f"{name!r} is registered as a counter")
            if name in self._gauges:
                raise TypeError(f"{name!r} is registered as a gauge")
            h = self._histograms.get(name)
            if h is None:
                if bounds is None:
                    raise KeyError(
                        f"histogram {name!r} does not exist yet — the first "
                        "accessor must declare its bucket bounds"
                    )
                h = self._histograms[name] = Histogram(name, bounds)
            elif bounds is not None and tuple(float(b) for b in bounds) != h.bounds:
                raise ValueError(
                    f"histogram {name!r} already exists with bounds "
                    f"{h.bounds}, not {tuple(bounds)}"
                )
            return h

    def inc(self, name: str, n: Number = 1) -> None:
        self.counter(name).inc(n)

    def set_gauge(self, name: str, value: Number) -> None:
        self.gauge(name).set(value)

    def observe(self, name: str, value: Number) -> None:
        """Observe into an EXISTING histogram (KeyError otherwise — an
        undeclared histogram has no bounds to bucket into)."""
        self.histogram(name).observe(value)

    def value(self, name: str, default: Number = 0) -> Number:
        """Current value of a counter, gauge, or flat histogram entry
        (``<hist>.count`` / ``<hist>.sum`` / ``<hist>.bucket.le_*``);
        ``default`` when the name was never touched (reading must not
        create entries — a baseline capture loop over candidate names
        stays side-effect free)."""
        with self._lock:
            if name in self._counters:
                return self._counters[name].value
            if name in self._gauges:
                return self._gauges[name].value
            hists = list(self._histograms.values())
        for h in hists:
            if name.startswith(h.name + "."):
                return h.flat().get(name, default)
        return default

    def snapshot(self) -> Dict[str, Number]:
        """Flat ``{name: value}`` of every counter, gauge, and
        histogram (flattened — see :meth:`Histogram.flat`), sorted by
        name (stable JSON diffs)."""
        with self._lock:
            out = {n: c.value for n, c in self._counters.items()}
            out.update({n: g.value for n, g in self._gauges.items()})
            hists = list(self._histograms.values())
        for h in hists:
            out.update(h.flat())
        return dict(sorted(out.items()))

    def snapshot_typed(self) -> Dict[str, Dict]:
        """``{"counters": {...}, "gauges": {...}, "histograms": {...}}``
        — the split the Prometheus exposition
        (:mod:`bcg_tpu.obs.export`) needs, since counter-vs-gauge-vs-
        histogram is a declared TYPE there, not a convention.  Each
        histogram entry carries its cumulative buckets, sum, and
        count."""
        with self._lock:
            counters = dict(
                sorted((n, c.value) for n, c in self._counters.items())
            )
            gauges = dict(
                sorted((n, g.value) for n, g in self._gauges.items())
            )
            hists = sorted(self._histograms.items())
        histograms = {
            name: {
                "buckets": [[b, c] for b, c in h.cumulative()],
                "sum": h.sum,
                "count": h.count,
            }
            for name, h in hists
        }
        return {"counters": counters, "gauges": gauges,
                "histograms": histograms}

    def delta(self, before: Dict[str, Number]) -> Dict[str, Number]:
        """COUNTER movement since ``before`` (a prior ``snapshot()``),
        nonzero entries only.  Histogram ``.count`` and ``.bucket.*``
        entries participate (they are monotonic observation counts);
        ``.sum`` does not (signed-observation histograms can move it
        down).  Gauges are excluded: a gauge's change is not "an amount
        of work done" and would pollute assertions like "exactly +1
        retrace"."""
        with self._lock:
            current = {n: c.value for n, c in self._counters.items()}
            hists = list(self._histograms.values())
        for h in hists:
            current.update({
                n: v for n, v in h.flat().items()
                if not n.endswith(".sum")
            })
        out = {
            n: v - before.get(n, 0)
            for n, v in current.items()
            if v - before.get(n, 0) != 0
        }
        return dict(sorted(out.items()))

    def reset(self) -> None:
        """Drop every counter, gauge, and histogram — TEST-ONLY (live
        consumers holding baseline values would see negative deltas)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


# The single process-wide registry.
REGISTRY = Registry()


# Module-level conveniences over REGISTRY (the call-site idiom:
# ``obs_counters.inc("engine.retrace.decode_loop")``).
def counter(name: str) -> Counter:
    return REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return REGISTRY.gauge(name)


def histogram(name: str, bounds: Optional[Iterable[float]] = None) -> Histogram:
    return REGISTRY.histogram(name, bounds)


def inc(name: str, n: Number = 1) -> None:
    REGISTRY.inc(name, n)


def set_gauge(name: str, value: Number) -> None:
    REGISTRY.set_gauge(name, value)


def observe(name: str, value: Number) -> None:
    REGISTRY.observe(name, value)


def value(name: str, default: Number = 0) -> Number:
    return REGISTRY.value(name, default)


def snapshot() -> Dict[str, Number]:
    return REGISTRY.snapshot()


def snapshot_typed() -> Dict[str, Dict[str, Number]]:
    return REGISTRY.snapshot_typed()


def delta(before: Dict[str, Number]) -> Dict[str, Number]:
    return REGISTRY.delta(before)


def reset() -> None:
    REGISTRY.reset()
