"""Process-wide counter/gauge registry.

One :data:`REGISTRY` per process, holding named monotonic
:class:`Counter`\\ s and settable :class:`Gauge`\\ s.  Layers increment
into it directly (the serve scheduler's linger buckets, the engine's
compile/retrace accounting); consumers read it three ways:

* ``snapshot()`` — flat ``{name: value}`` dict of every counter and
  gauge, the form ``bench.py`` attaches to its JSON (success AND error);
* ``delta(before)`` — counter movement since an earlier ``snapshot()``,
  the form tests assert on ("this scripted run incremented
  ``engine.retrace.decode_loop`` by exactly 1");
* per-instance baselines — a consumer that needs *its own* share of a
  process-wide counter (e.g. one scheduler's linger histogram while
  another may have run earlier in the process) records ``value(name)`` at
  construction and subtracts it at read time.

Counters are strictly monotonic (``inc`` rejects negative amounts):
a counter that can go down is a gauge, and mixing the two breaks
``delta()``'s "movement since" semantics.  No jax import — this module
must stay loadable by flag-only consumers (bench.py's error path).
"""

from __future__ import annotations

import threading
from typing import Dict, Union

Number = Union[int, float]


class Counter:
    """Named monotonic counter."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value: Number = 0
        self._lock = threading.Lock()

    def inc(self, n: Number = 1) -> None:
        if n < 0:
            raise ValueError(
                f"counter {self.name!r}: inc({n}) — counters are "
                "monotonic; use a gauge for values that go down"
            )
        with self._lock:
            self._value += n

    @property
    def value(self) -> Number:
        return self._value


class Gauge:
    """Named point-in-time value (last set wins)."""

    __slots__ = ("name", "_value")

    def __init__(self, name: str):
        self.name = name
        self._value: Number = 0

    def set(self, value: Number) -> None:
        self._value = value

    @property
    def value(self) -> Number:
        return self._value


class Registry:
    """Name -> Counter/Gauge map; create-on-first-use accessors."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            if name in self._gauges:
                raise TypeError(f"{name!r} is registered as a gauge")
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            if name in self._counters:
                raise TypeError(f"{name!r} is registered as a counter")
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name)
            return g

    def inc(self, name: str, n: Number = 1) -> None:
        self.counter(name).inc(n)

    def set_gauge(self, name: str, value: Number) -> None:
        self.gauge(name).set(value)

    def value(self, name: str, default: Number = 0) -> Number:
        """Current value of a counter or gauge; ``default`` when the
        name was never touched (reading must not create entries — a
        baseline capture loop over candidate names stays side-effect
        free)."""
        with self._lock:
            if name in self._counters:
                return self._counters[name].value
            if name in self._gauges:
                return self._gauges[name].value
        return default

    def snapshot(self) -> Dict[str, Number]:
        """Flat ``{name: value}`` of every counter and gauge, sorted by
        name (stable JSON diffs)."""
        with self._lock:
            out = {n: c.value for n, c in self._counters.items()}
            out.update({n: g.value for n, g in self._gauges.items()})
        return dict(sorted(out.items()))

    def snapshot_typed(self) -> Dict[str, Dict[str, Number]]:
        """``{"counters": {...}, "gauges": {...}}`` — the split the
        Prometheus exposition (:mod:`bcg_tpu.obs.export`) needs, since
        counter-vs-gauge is a declared TYPE there, not a convention."""
        with self._lock:
            return {
                "counters": dict(
                    sorted((n, c.value) for n, c in self._counters.items())
                ),
                "gauges": dict(
                    sorted((n, g.value) for n, g in self._gauges.items())
                ),
            }

    def delta(self, before: Dict[str, Number]) -> Dict[str, Number]:
        """COUNTER movement since ``before`` (a prior ``snapshot()``),
        nonzero entries only.  Gauges are excluded: a gauge's change is
        not "an amount of work done" and would pollute assertions like
        "exactly +1 retrace"."""
        with self._lock:
            current = {n: c.value for n, c in self._counters.items()}
        out = {
            n: v - before.get(n, 0)
            for n, v in current.items()
            if v - before.get(n, 0) != 0
        }
        return dict(sorted(out.items()))

    def reset(self) -> None:
        """Drop every counter and gauge — TEST-ONLY (live consumers
        holding baseline values would see negative deltas)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()


# The single process-wide registry.
REGISTRY = Registry()


# Module-level conveniences over REGISTRY (the call-site idiom:
# ``obs_counters.inc("engine.retrace.decode_loop")``).
def counter(name: str) -> Counter:
    return REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return REGISTRY.gauge(name)


def inc(name: str, n: Number = 1) -> None:
    REGISTRY.inc(name, n)


def set_gauge(name: str, value: Number) -> None:
    REGISTRY.set_gauge(name, value)


def value(name: str, default: Number = 0) -> Number:
    return REGISTRY.value(name, default)


def snapshot() -> Dict[str, Number]:
    return REGISTRY.snapshot()


def snapshot_typed() -> Dict[str, Dict[str, Number]]:
    return REGISTRY.snapshot_typed()


def delta(before: Dict[str, Number]) -> Dict[str, Number]:
    return REGISTRY.delta(before)


def reset() -> None:
    REGISTRY.reset()
