"""Observability: span tracer + counter/gauge registry.

``bcg_tpu.obs.tracer`` — nestable, cross-thread spans with explicit
parent handoff, ring-buffered, exported as Chrome trace-event JSON
(Perfetto-loadable; ``scripts/trace_report.py`` prints the latency
table + top counters from an export).  ``bcg_tpu.obs.counters`` — the
single process-wide counter/gauge registry (compile/retrace accounting,
serve linger buckets) with ``snapshot()``/``delta()`` for tests and
bench JSON.

Neither module imports jax: flag-only consumers (bench.py's error
path) stay light.  Enable tracing with ``BCG_TPU_TRACE=1``; see
DESIGN.md "Observability" for the span taxonomy.
"""

from bcg_tpu.obs import counters, tracer  # noqa: F401

__all__ = ["counters", "tracer"]
