"""Observability: span tracer, counter/gauge registry, and the
device-cost half — HLO census, HBM ledger, telemetry export.

``bcg_tpu.obs.tracer`` — nestable, cross-thread spans with explicit
parent handoff, ring-buffered, exported as Chrome trace-event JSON
(Perfetto-loadable; ``scripts/trace_report.py`` prints the latency
table + top counters from an export).  ``bcg_tpu.obs.counters`` — the
single process-wide counter/gauge/histogram registry (compile/retrace
accounting, the serve latency + SLO-headroom histograms) with
``snapshot()``/``delta()`` for tests and bench JSON.
``bcg_tpu.obs.game_events`` — the consensus-game event stream
(``BCG_TPU_GAME_EVENTS`` JSONL + live ``game.*`` metrics;
``scripts/consensus_report.py`` aggregates the files into
convergence tables).  ``bcg_tpu.obs.hlo`` — lowered-HLO kernel census per jit
entry (``engine.hlo.*`` gauges; ``scripts/hlo_census.py`` +
``hlo_baseline.json`` pin kernel counts per decode step).
``bcg_tpu.obs.ledger`` — per-device HBM byte accounting of params / KV
slabs / prefix entries / spec slots (``hbm.*`` gauges).
``bcg_tpu.obs.export`` — Prometheus text exposition, the
``BCG_TPU_SERVE_EVENTS`` request-lifecycle JSONL sink, and the
``BCG_TPU_METRICS_PORT`` HTTP ``/metrics`` endpoint.
``bcg_tpu.obs.hostsync`` — runtime host↔device transfer auditor
(``BCG_TPU_HOSTSYNC``): per-sync span/jit-entry attribution
(``engine.hostsync.*``), the ``game.host_syncs`` per-round histogram,
and the perf_gate ``hostsync`` drift gate for ROADMAP item 1's
host-syncs-per-round target.

None of these modules import jax at module scope: flag-only consumers
(bench.py's error path) stay light.  Enable tracing with
``BCG_TPU_TRACE=1``; see DESIGN.md "Observability" for the span
taxonomy and the device-cost subsection.
"""

from bcg_tpu.obs import counters, export, hlo, hostsync, ledger, tracer  # noqa: F401

# game_events is NOT imported eagerly: it pulls game.statistics, which
# flag-only consumers never need; the orchestrator imports it directly.
__all__ = ["counters", "export", "hlo", "hostsync", "ledger", "tracer"]
