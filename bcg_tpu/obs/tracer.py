"""Span tracer: nestable, cross-thread spans → Chrome trace-event JSON.

The serving stack's latency is spread over threads — a game thread
builds prompts and blocks on its request future, the scheduler thread
forms batches and runs the device — so a slow round could be queue
wait, a retrace, or a KV-admission stall and per-phase wall-clock sums
cannot say which.  Spans can: every instrumented layer opens named
spans (``round`` → ``decide`` → ``serve.request`` → … →
``engine.decode``), events land in a bounded ring buffer, and
``export()`` writes Chrome trace-event JSON loadable in Perfetto
(ui.perfetto.dev) with per-thread nesting intact.

Mechanics:

* **Nesting** is thread-local: a span's parent defaults to the top of
  the CURRENT thread's open-span stack.
* **Cross-thread parent handoff** is explicit: a layer that carries
  work across threads stashes the originating span handle (e.g.
  ``Request.span`` in ``bcg_tpu/serve/scheduler.py``) and passes it as
  ``parent=`` when it resumes on the other thread; the exported events
  carry ``span_id``/``parent_id`` in ``args`` so the lineage survives
  the thread boundary (Perfetto still nests per-thread; the ids are the
  ground truth for tools and tests).
* **B/E pairs** come from the ``span()`` context manager and are always
  balanced (the exit records in a ``finally``); ``complete()`` records
  an already-measured interval as a single ``X`` (complete) event —
  used where an interval's endpoints live on different threads (a
  request's enqueue→dispatch ``queue_wait``).
* **Ring buffer**: the event deque holds the last
  ``BCG_TPU_TRACE_RING`` events; a long run keeps its tail, and the
  per-name latency accumulator (:class:`SpanAggregator`) is NOT subject
  to eviction, so ``summarize()`` covers the whole run.

Enablement: ``BCG_TPU_TRACE=1`` (or setting ``BCG_TPU_TRACE_OUT``,
which also registers an atexit export to that path).  Flags are read
ONCE at first use — a per-span env read would be measurable overhead on
hot paths; tests reconfigure via :func:`reset`.  When disabled, the
module-level :func:`span` returns a shared no-op context manager whose
cost is bounded by test (``tests/test_obs.py`` disabled-overhead
bound); call sites therefore never need their own ``if traced:`` guard.

No jax import — loadable by flag-only consumers (bench.py error path).
"""

from __future__ import annotations

import atexit
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from bcg_tpu.obs import counters as _counters
from bcg_tpu.runtime import envflags

# Bounded per-name duration reservoir for p50/p95 (newest-biased: a
# steady-state regression shows up; exact quantiles over unbounded
# history would grow without bound on long serving runs).
_SAMPLE_CAP = 512


def percentile(sorted_samples: List[float], q: float) -> float:
    """Nearest-rank percentile over an ascending-sorted list."""
    if not sorted_samples:
        return 0.0
    idx = max(0, min(len(sorted_samples) - 1,
                     int(round(q * (len(sorted_samples) - 1)))))
    return sorted_samples[idx]


class SpanAggregator:
    """Per-name latency accumulator: count/total plus a bounded sample
    reservoir for p50/p95.  Shared by :meth:`Tracer.summarize`, the
    ``SimulationProfiler`` (which delegates its phase timing here), and
    the serve scheduler's per-stage ``latency_ms`` snapshot — one
    aggregation implementation, three consumers."""

    def __init__(self):
        self._lock = threading.Lock()
        # name -> [count, total_seconds, deque(samples)]
        self._stats: Dict[str, list] = {}

    def add(self, name: str, seconds: float) -> None:
        with self._lock:
            st = self._stats.get(name)
            if st is None:
                st = self._stats[name] = [0, 0.0, deque(maxlen=_SAMPLE_CAP)]
            st[0] += 1
            st[1] += seconds
            st[2].append(seconds)

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return {n: st[0] for n, st in self._stats.items()}

    def totals(self) -> Dict[str, float]:
        with self._lock:
            return {n: st[1] for n, st in self._stats.items()}

    def table(self) -> Dict[str, Dict[str, float]]:
        """{name: {count, total_ms, mean_ms, p50_ms, p95_ms}}, sorted
        by total descending (the hot row first)."""
        with self._lock:
            rows = {}
            for name, (count, total, samples) in self._stats.items():
                ordered = sorted(samples)
                rows[name] = {
                    "count": count,
                    "total_ms": round(total * 1e3, 3),
                    "mean_ms": round(total * 1e3 / count, 3) if count else 0.0,
                    "p50_ms": round(percentile(ordered, 0.50) * 1e3, 3),
                    "p95_ms": round(percentile(ordered, 0.95) * 1e3, 3),
                }
        return dict(
            sorted(rows.items(), key=lambda kv: -kv[1]["total_ms"])
        )


class SpanHandle:
    """Identity of one open (or finished) span — what cross-thread
    callers pass as ``parent=``."""

    __slots__ = ("name", "span_id", "parent_id", "tid")

    def __init__(self, name: str, span_id: int, parent_id: Optional[int],
                 tid: int):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.tid = tid


class _NullSpan:
    """Shared no-op context manager — the disabled-tracer fast path."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL_SPAN = _NullSpan()


class _TimedOnly:
    """Times the block and feeds a :class:`SpanAggregator`, recording no
    events — what ``span(aggregate=...)`` degrades to when tracing is
    off (the profiler's phase timing must keep working untraced: it
    feeds the metrics CSV)."""

    __slots__ = ("_agg", "_name", "_t0")

    def __init__(self, agg: SpanAggregator, name: str):
        self._agg = agg
        self._name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return None

    def __exit__(self, exc_type, exc, tb):
        self._agg.add(self._name, time.perf_counter() - self._t0)
        return False


class _SpanCm:
    """Context manager for one traced span (B event on enter, E on
    exit — the exit runs unconditionally, so B/E stay balanced even
    when the body raises)."""

    __slots__ = ("_tracer", "_name", "_parent", "_args", "_aggregate",
                 "_handle", "_t0")

    def __init__(self, tracer: "Tracer", name: str,
                 parent: Optional[SpanHandle], args: Optional[Dict],
                 aggregate: Optional[SpanAggregator]):
        self._tracer = tracer
        self._name = name
        self._parent = parent
        self._args = args
        self._aggregate = aggregate

    def __enter__(self) -> SpanHandle:
        self._t0 = time.perf_counter()
        self._handle = self._tracer._begin(
            self._name, self._parent, self._args, self._t0
        )
        return self._handle

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        self._tracer._end(self._handle, t1, failed=exc_type is not None)
        seconds = t1 - self._t0
        if self._aggregate is not None:
            self._aggregate.add(self._name, seconds)
        self._tracer._agg.add(self._name, seconds)
        return False


class Tracer:
    """Thread-safe span recorder over a bounded event ring."""

    def __init__(self, ring_capacity: int = 65536):
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=max(16, int(ring_capacity)))
        self._ids = itertools.count(1)
        self._local = threading.local()
        self._epoch = time.perf_counter()
        self._agg = SpanAggregator()
        self._thread_names: Dict[int, str] = {}

    # ------------------------------------------------------------- recording

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def current(self) -> Optional[SpanHandle]:
        """Top of the calling thread's open-span stack (None outside any
        span) — what layers stash for cross-thread parent handoff."""
        stack = getattr(self._local, "stack", None)
        return stack[-1] if stack else None

    def _note_thread(self, tid: int) -> None:
        if tid not in self._thread_names:
            self._thread_names[tid] = threading.current_thread().name

    def _begin(self, name: str, parent: Optional[SpanHandle],
               args: Optional[Dict], t0: float) -> SpanHandle:
        tid = threading.get_ident()
        stack = self._stack()
        if parent is None and stack:
            parent = stack[-1]
        handle = SpanHandle(
            name, next(self._ids),
            parent.span_id if parent is not None else None, tid,
        )
        stack.append(handle)
        ts = (t0 - self._epoch) * 1e6
        with self._lock:
            self._note_thread(tid)
            self._events.append(
                ("B", name, ts, tid, handle.span_id, handle.parent_id,
                 dict(args) if args else None, None)
            )
        return handle

    def _end(self, handle: SpanHandle, t1: float, failed: bool = False) -> None:
        stack = self._stack()
        # Pop down to (and including) this handle: a body that leaked an
        # unclosed child must not corrupt the stack for later spans.
        while stack and stack[-1] is not handle:
            stack.pop()
        if stack:
            stack.pop()
        ts = (t1 - self._epoch) * 1e6
        with self._lock:
            self._events.append(
                ("E", handle.name, ts, handle.tid, handle.span_id, None,
                 {"failed": True} if failed else None, None)
            )

    def span(self, name: str, parent: Optional[SpanHandle] = None,
             args: Optional[Dict] = None,
             aggregate: Optional[SpanAggregator] = None) -> _SpanCm:
        return _SpanCm(self, name, parent, args, aggregate)

    def complete(self, name: str, seconds: float,
                 parent: Optional[SpanHandle] = None,
                 args: Optional[Dict] = None) -> None:
        """Record an already-measured interval ending NOW as one ``X``
        event (for intervals whose start lived on another thread —
        enqueue→dispatch waits)."""
        tid = threading.get_ident()
        end = time.perf_counter()
        ts = (end - seconds - self._epoch) * 1e6
        with self._lock:
            self._note_thread(tid)
            self._events.append(
                ("X", name, ts, tid, next(self._ids),
                 parent.span_id if parent is not None else None,
                 dict(args) if args else None, seconds * 1e6)
            )
        self._agg.add(name, seconds)

    # --------------------------------------------------------------- reading

    def events(self) -> List[Tuple]:
        with self._lock:
            return list(self._events)

    def summarize(self) -> Dict[str, Dict[str, float]]:
        """Per-span-name latency table (count/total/p50/p95) over the
        WHOLE run — the aggregator is not subject to ring eviction."""
        return self._agg.table()

    def export(self, path: Optional[str] = None) -> Dict[str, Any]:
        """Chrome trace-event JSON (Perfetto-loadable).  ``ts`` is µs
        since tracer epoch; ``args.span_id``/``args.parent_id`` carry
        the explicit lineage; counters ride in ``otherData`` so one file
        holds the full observability state."""
        with self._lock:
            events = list(self._events)
            threads = dict(self._thread_names)
        pid = os.getpid()
        trace_events: List[Dict[str, Any]] = [
            {
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                "args": {"name": tname},
            }
            for tid, tname in sorted(threads.items())
        ]
        for ph, name, ts, tid, span_id, parent_id, args, dur in events:
            ev: Dict[str, Any] = {
                "name": name, "cat": "bcg", "ph": ph,
                "ts": round(ts, 3), "pid": pid, "tid": tid,
                "args": {"span_id": span_id},
            }
            if parent_id is not None:
                ev["args"]["parent_id"] = parent_id
            if args:
                ev["args"].update(args)
            if dur is not None:
                ev["dur"] = round(dur, 3)
            trace_events.append(ev)
        data = {
            "traceEvents": trace_events,
            "displayTimeUnit": "ms",
            "otherData": {
                "counters": _counters.snapshot(),
                "span_summary": self.summarize(),
            },
        }
        # Fleet identity (run id, rank, host) so traces from many ranks
        # of one run stay attributable after they are copied off-host.
        from bcg_tpu.obs import fleet as _fleet

        if _fleet.enabled():
            data["otherData"]["fleet"] = _fleet.identity()
        if path:
            with open(path, "w") as f:
                json.dump(data, f)
        return data


# ---------------------------------------------------------- module surface
_config_lock = threading.Lock()
_tracer: Optional[Tracer] = None
_configured = False


def _ensure() -> Optional[Tracer]:
    global _tracer, _configured
    if _configured:
        return _tracer
    with _config_lock:
        if not _configured:
            out = envflags.get_str("BCG_TPU_TRACE_OUT")
            enabled = envflags.get_bool("BCG_TPU_TRACE") or bool(out)
            if enabled:
                _tracer = Tracer(envflags.get_int("BCG_TPU_TRACE_RING"))
                if out:
                    atexit.register(flush)
            _configured = True
    return _tracer


def get_tracer() -> Optional[Tracer]:
    """The active tracer, or None when tracing is disabled."""
    return _ensure()


def enabled() -> bool:
    return _ensure() is not None


def span(name: str, parent: Optional[SpanHandle] = None,
         args: Optional[Dict] = None,
         aggregate: Optional[SpanAggregator] = None):
    """Open a span on the active tracer; no-op (shared singleton) when
    tracing is disabled — unless ``aggregate`` is given, in which case
    the block is still timed into the aggregate (profiler semantics)."""
    t = _tracer if _configured else _ensure()
    if t is not None:
        return t.span(name, parent=parent, args=args, aggregate=aggregate)
    if aggregate is not None:
        return _TimedOnly(aggregate, name)
    return _NULL_SPAN


def current() -> Optional[SpanHandle]:
    """Calling thread's innermost open span (None when disabled/none)."""
    t = _tracer if _configured else _ensure()
    return t.current() if t is not None else None


def complete(name: str, seconds: float,
             parent: Optional[SpanHandle] = None,
             args: Optional[Dict] = None) -> None:
    t = _tracer if _configured else _ensure()
    if t is not None:
        t.complete(name, seconds, parent=parent, args=args)


def summarize() -> Optional[Dict[str, Dict[str, float]]]:
    t = _tracer if _configured else _ensure()
    return t.summarize() if t is not None else None


def flush() -> Optional[str]:
    """Export to the configured ``BCG_TPU_TRACE_OUT`` path (atexit hook;
    also callable directly).  Returns the path written, or None."""
    t = _tracer if _configured else _ensure()
    out = envflags.get_str("BCG_TPU_TRACE_OUT")
    if t is None or not out:
        return None
    t.export(out)
    return out


def reset() -> None:
    """Drop the cached tracer AND its read-once flag cache so the next
    use re-reads the environment — TEST-ONLY."""
    global _tracer, _configured
    with _config_lock:
        _tracer = None
        _configured = False
