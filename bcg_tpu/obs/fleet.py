"""Fleet observability plane: process identity, metric shards,
heartbeats, and straggler detection.

Every other instrument in :mod:`bcg_tpu.obs` is process-local: counter
snapshots, the Prometheus endpoint, the tracer ring, and both JSONL
sinks describe ONE process and carry no identity beyond a pid.  A
2-host run therefore yields two disjoint telemetry islands — and a
silent hang when one rank stalls.  This module makes every existing
signal host-aware and mergeable:

* **Identity** — one process-wide :func:`identity`: ``run_id`` (shared
  across ranks via ``BCG_TPU_RUN_ID``, else a per-process 12-hex id),
  ``process_index``/``process_count`` (from
  :mod:`bcg_tpu.parallel.distributed` once the JAX process group is
  initialized — :func:`set_process_provider` — else ``0``/``1``),
  hostname, and pid.  Stamped into the run manifest of BOTH JSONL
  sinks, the tracer export, and — when :func:`enabled` — the
  Prometheus exposition as ``process=``/``host=`` labels so multi-rank
  scrapes don't collide into one anonymous metric family.
* **Metric shards** — ``BCG_TPU_METRICS_SHARD_DIR=<dir>``: a periodic
  flusher thread (:class:`ShardWriter`) appends this process's typed
  counter/gauge/histogram snapshot as one JSONL record per flush to
  ``shard-<run_id>-<process_index>.jsonl``.  Counters merge by SUM,
  histograms bucket-wise (fixed bounds make two histograms addable),
  gauges keep per-rank values — ``scripts/fleet_report.py`` (bcg_tpu-
  import-free) does the merge offline.
* **Liveness** — each flush sets the ``fleet.heartbeat_ms`` gauge
  (epoch ms of the last flush) and re-publishes the ``fleet.watermark``
  progress gauge the orchestrator (per round) and serve scheduler (per
  dispatch) advance through :func:`note_round`/:func:`note_dispatch`.
* **Straggler detection** — :func:`check_stragglers` reads the peer
  shards' newest records and flags ranks whose watermark or heartbeat
  lags the fleet median by ``BCG_TPU_FLEET_STRAGGLER_FACTOR`` (0 =
  off), publishing the count as the ``fleet.stragglers`` gauge.  The
  same rule, by value, lives in ``scripts/fleet_report.py --watch``.
  :func:`freeze_watermark` is the documented chaos hook the perf-gate
  "fleet" scenario uses to inject a straggler rank — detection is
  gated against it, never vacuously green.

Stamping is OFF in a default single-process run (no flags, no process
group): no ``fleet.*`` registry entries are created and the Prometheus
exposition stays byte-identical to the unstamped form — the acceptance
contract ``tests/test_fleet.py`` pins.

No jax import — loadable by flag-only consumers (bench.py error path);
the process provider closure (set by ``parallel/distributed.py``) is
the only thing that ever touches the backend, and only after
``jax.distributed.initialize`` succeeded.
"""

from __future__ import annotations

import atexit
import json
import os
import socket
import statistics
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from bcg_tpu.obs import counters as obs_counters
from bcg_tpu.runtime import envflags

# Schema of one shard record (bump on breaking field changes —
# scripts/fleet_report.py mirrors this by value, not import).
SHARD_SCHEMA_VERSION = 1

_state_lock = threading.Lock()
_run_id: Optional[str] = None
_process_provider: Optional[Callable[[], Tuple[int, int]]] = None
_process: Optional[Tuple[int, int]] = None
_watermark = 0
_watermark_frozen = False
_writer: Optional["ShardWriter"] = None
_writer_configured = False
_last_straggler_check = 0.0


# ------------------------------------------------------------------ identity
def set_process_provider(provider: Callable[[], Tuple[int, int]]) -> None:
    """Install the ``() -> (process_index, process_count)`` source —
    called by :func:`bcg_tpu.parallel.distributed.initialize` once the
    JAX process group exists.  Lazy by design: querying the backend
    inside ``initialize()`` itself would force backend creation earlier
    than callers expect."""
    global _process_provider, _process
    with _state_lock:
        _process_provider = provider
        _process = None  # re-resolve on next read


def _resolve_process() -> Tuple[int, int]:
    global _process
    with _state_lock:
        if _process is not None:
            return _process
        provider = _process_provider
    if provider is None:
        pair = (0, 1)
    else:
        try:
            idx, count = provider()
            pair = (int(idx), int(count))
        except Exception:
            # Backend torn down mid-exit: stay single-process rather
            # than taking telemetry down with it.
            pair = (0, 1)
    with _state_lock:
        _process = pair
    return pair


def process_index() -> int:
    return _resolve_process()[0]


def process_count() -> int:
    return _resolve_process()[1]


def run_id() -> str:
    """The run id every shard/manifest of this process carries:
    ``BCG_TPU_RUN_ID`` when the launcher set one (all ranks of one run
    share it — the shard-merge key), else a stable per-process 12-hex
    id."""
    global _run_id
    configured = envflags.get_str("BCG_TPU_RUN_ID")
    if configured:
        return configured
    with _state_lock:
        if _run_id is None:
            import uuid

            _run_id = uuid.uuid4().hex[:12]
        return _run_id


def identity() -> Dict[str, Any]:
    """The process's fleet identity — what manifests, shard records,
    the tracer export, and bench's ``fleet`` block carry."""
    idx, count = _resolve_process()
    return {
        "run_id": run_id(),
        "process_index": idx,
        "process_count": count,
        "host": socket.gethostname(),
        "pid": os.getpid(),
    }


def enabled() -> bool:
    """Fleet stamping on?  True when ``BCG_TPU_FLEET=1``, a shard dir
    is configured, or this process joined a multi-process group.  The
    default single-process path is OFF: no ``fleet.*`` registry
    entries, and the Prometheus exposition is byte-identical to the
    unstamped form."""
    if envflags.get_bool("BCG_TPU_FLEET"):
        return True
    if envflags.get_str("BCG_TPU_METRICS_SHARD_DIR"):
        return True
    return process_count() > 1


def _escape_label(value: str) -> str:
    return (
        value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def prom_label_body() -> str:
    """The identity label body for Prometheus samples
    (``process="3",host="worker-a"``), or ``""`` when stamping is off —
    the empty form keeps the exposition byte-identical to the
    unstamped renderer."""
    if not enabled():
        return ""
    ident = identity()
    return (
        f'process="{ident["process_index"]}",'
        f'host="{_escape_label(ident["host"])}"'
    )


def _publish_identity_gauges() -> None:
    idx, count = _resolve_process()
    obs_counters.set_gauge("fleet.process_index", idx)
    obs_counters.set_gauge("fleet.process_count", count)


# ------------------------------------------------------- liveness watermarks
def heartbeat() -> float:
    """Set ``fleet.heartbeat_ms`` to now (epoch ms) and return it.
    Epoch time, not monotonic, deliberately: heartbeats are compared
    ACROSS processes, where each rank's monotonic clock is meaningless
    to its peers."""
    # Chaos seam (BCG_TPU_CHAOS `freeze@fleet.heartbeat`): the injected
    # rank-freeze generalizes freeze_watermark() — the rank keeps
    # heartbeating and flushing shards, but its progress watermark
    # stops, so peers must flag it by lag (the straggler rule's prey).
    from bcg_tpu.runtime import resilience

    resilience.inject("fleet.heartbeat")
    now_ms = time.time() * 1e3
    obs_counters.set_gauge("fleet.heartbeat_ms", now_ms)
    return now_ms


def note_round() -> None:
    """Advance the progress watermark by one game round (orchestrator
    ``run_round``).  No-op when stamping is off (no gauge registered)
    or the watermark is frozen (injected-straggler chaos hook)."""
    _advance_watermark()


def note_dispatch() -> None:
    """Advance the progress watermark by one serve dispatch."""
    _advance_watermark()


def _advance_watermark() -> None:
    global _watermark
    if not enabled():
        return
    with _state_lock:
        if _watermark_frozen:
            return
        _watermark += 1
        value = _watermark
    if value == 1:
        # First progress of an enabled run: land the identity gauges in
        # counter snapshots even when no shard flusher is running.
        _publish_identity_gauges()
    obs_counters.set_gauge("fleet.watermark", value)


def freeze_watermark() -> None:
    """CHAOS HOOK: stop this rank's watermark from ever advancing — the
    injected-straggler arm of the perf-gate "fleet" scenario.  The rank
    keeps heartbeating and flushing shards; peers must flag it by
    watermark lag (never vacuously green)."""
    global _watermark_frozen
    with _state_lock:
        _watermark_frozen = True


# ------------------------------------------------------------ metric shards
class ShardWriter:
    """Periodic flusher: every ``flush_ms`` it heartbeats, snapshots
    the typed registry, and appends one JSONL record to this process's
    shard file.  The writer owns its thread (the EventSink idiom — a
    stalled disk never blocks a round loop; here emission itself
    already lives off the hot path) and warns once then stops on write
    failure rather than spinning a dead disk."""

    def __init__(self, shard_dir: str, flush_ms: int):
        os.makedirs(shard_dir, exist_ok=True)
        self.flush_ms = max(50, int(flush_ms))
        ident = identity()
        self.path = os.path.join(
            shard_dir,
            f"shard-{ident['run_id']}-{ident['process_index']}.jsonl",
        )
        self._lock = threading.Lock()
        self._fh = None
        self._write_failed = False
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="bcg-fleet-shard", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.flush_ms / 1e3):
            self.flush()
            check_stragglers()
        self.flush()  # final flush on close()

    def flush(self) -> None:
        """Write one shard record NOW (also called for the final flush
        on close/atexit so a normal exit loses nothing)."""
        hb = heartbeat()
        _publish_identity_gauges()
        record = {
            "ts": time.time(),
            "schema_version": SHARD_SCHEMA_VERSION,
            "identity": identity(),
            "flush_ms": self.flush_ms,
            "heartbeat_ms": hb,
        }
        record.update(obs_counters.snapshot_typed())
        line = json.dumps(record, default=str) + "\n"
        with self._lock:
            if self._write_failed:
                return
            try:
                if self._fh is None:
                    self._fh = open(self.path, "a", encoding="utf-8")
                self._fh.write(line)
                self._fh.flush()
            except OSError as exc:
                import sys

                print(
                    f"obs.fleet: shard write failed ({self.path}): {exc} "
                    "— further shard flushes dropped",
                    file=sys.stderr,
                )
                self._write_failed = True

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10.0)
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


# Guards writer configuration only (never nested inside _state_lock:
# ShardWriter construction reads identity() which takes _state_lock).
_writer_lock = threading.Lock()


def maybe_start_shard_writer() -> Optional[ShardWriter]:
    """Start the process shard flusher once when
    ``BCG_TPU_METRICS_SHARD_DIR`` is set; None when disabled.  Called
    from the same boot sites as ``maybe_start_http_server`` (engine
    boot, scheduler boot, game recorder) — cheap no-op afterwards."""
    global _writer, _writer_configured
    if _writer_configured:
        return _writer
    with _writer_lock:
        if not _writer_configured:
            shard_dir = envflags.get_str("BCG_TPU_METRICS_SHARD_DIR")
            if shard_dir:
                _writer = ShardWriter(
                    shard_dir, envflags.get_int("BCG_TPU_METRICS_SHARD_MS")
                )
                atexit.register(_close_writer)
            _writer_configured = True
    return _writer


def _close_writer() -> None:
    with _writer_lock:
        writer = _writer
    if writer is not None:
        writer.close()


def flush_shards() -> None:
    """Force one shard flush now (workers call this right before exit;
    the atexit close also flushes)."""
    writer = maybe_start_shard_writer()
    if writer is not None:
        writer.flush()


def shard_path() -> Optional[str]:
    writer = maybe_start_shard_writer()
    return writer.path if writer is not None else None


# ------------------------------------------------------ straggler detection
def read_last_record(path: str) -> Optional[Dict[str, Any]]:
    """Newest JSONL record of one shard file (shards are cumulative
    snapshots, so the last line IS the rank's current state).  Reads a
    bounded tail, not the whole file — peers poll this per flush."""
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - 262144))
            tail = f.read().decode("utf-8", errors="replace")
    except OSError:
        return None
    for line in reversed(tail.strip().splitlines()):
        line = line.strip()
        if not line:
            continue
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue  # truncated mid-write: take the previous line
    return None


def peer_records(shard_dir: str, run: str) -> List[Dict[str, Any]]:
    """Newest record per rank of ``run`` in ``shard_dir`` (own rank
    included)."""
    records = []
    try:
        names = sorted(os.listdir(shard_dir))
    except OSError:
        return records
    prefix = f"shard-{run}-"
    for name in names:
        if not (name.startswith(prefix) and name.endswith(".jsonl")):
            continue
        rec = read_last_record(os.path.join(shard_dir, name))
        if rec is not None:
            records.append(rec)
    return records


def detect_stragglers(
    records: List[Dict[str, Any]],
    factor: float,
    now_ms: Optional[float] = None,
) -> List[Dict[str, Any]]:
    """Ranks lagging the fleet, from a set of newest shard records.

    Two independent lag rules, both relative to the fleet so absolute
    speed never matters:

    * **watermark** — ``rank_watermark * factor < median(watermarks)``:
      the rank made less than 1/factor of the median progress;
    * **heartbeat** — the rank's last heartbeat is more than
      ``factor * flush_ms`` behind ``now_ms`` (live check) or behind
      the freshest rank (offline replay): its flusher stopped.

    ``factor <= 0`` disables detection; fewer than 2 ranks can have no
    median to lag.  Mirrored by value in ``scripts/fleet_report.py``
    (which must stay bcg_tpu-import-free) — ``tests/test_fleet.py``
    holds the two implementations to the same verdicts.
    """
    if factor <= 0 or len(records) < 2:
        return []
    gauges = [r.get("gauges") or {} for r in records]
    watermarks = [float(g.get("fleet.watermark", 0)) for g in gauges]
    heartbeats = [
        float(r.get("heartbeat_ms") or g.get("fleet.heartbeat_ms", 0))
        for r, g in zip(records, gauges)
    ]
    med_watermark = statistics.median(watermarks)
    ref_ms = now_ms if now_ms is not None else max(heartbeats, default=0.0)
    out = []
    for rec, w, hb in zip(records, watermarks, heartbeats):
        reasons = []
        if med_watermark > 0 and w * factor < med_watermark:
            reasons.append("watermark")
        flush_ms = float(rec.get("flush_ms") or 1000.0)
        if hb > 0 and (ref_ms - hb) > factor * flush_ms:
            reasons.append("heartbeat")
        if reasons:
            ident = rec.get("identity") or {}
            out.append({
                "process_index": ident.get("process_index"),
                "host": ident.get("host"),
                "reasons": reasons,
                "watermark": w,
                "median_watermark": med_watermark,
                "heartbeat_age_ms": round(ref_ms - hb, 1) if hb else None,
            })
    return out


def check_stragglers(force: bool = False) -> List[Dict[str, Any]]:
    """Runtime straggler pass: read the peer shards and publish the
    lagging-rank count as the ``fleet.stragglers`` gauge.  Rate-limited
    to one pass per flush period (the scheduler calls this per
    dispatch; a hot serving loop must not turn it into a disk scan per
    batch) unless ``force``.  No-ops when shards or detection
    (``BCG_TPU_FLEET_STRAGGLER_FACTOR=0``) are off."""
    global _last_straggler_check
    writer = maybe_start_shard_writer()
    if writer is None:
        return []
    factor = envflags.get_int("BCG_TPU_FLEET_STRAGGLER_FACTOR")
    if factor <= 0:
        return []
    now = time.monotonic()
    with _state_lock:
        if not force and now - _last_straggler_check < writer.flush_ms / 1e3:
            return []
        _last_straggler_check = now
    records = peer_records(os.path.dirname(writer.path), run_id())
    flagged = detect_stragglers(records, factor, now_ms=time.time() * 1e3)
    obs_counters.set_gauge("fleet.stragglers", len(flagged))
    return flagged


# ------------------------------------------------------------------- summary
def summary() -> Optional[Dict[str, Any]]:
    """The bench JSON ``fleet`` block: identity, shard path, heartbeat
    age, watermark, straggler count — attached on success AND error
    paths (a hung rank's last bench line should say which rank it was).
    None when stamping is off."""
    if not enabled():
        return None
    hb = obs_counters.value("fleet.heartbeat_ms", 0)
    # Heartbeats are epoch-ms BY DESIGN (compared across processes,
    # where each rank's monotonic clock is meaningless to its peers),
    # so the age is wall-clock arithmetic on purpose.
    age_ms = time.time() * 1e3 - hb  # lint: ignore[BCG-TIME-WALL]
    return {
        "identity": identity(),
        "shard_path": shard_path(),
        "heartbeat_age_ms": round(age_ms, 1) if hb else None,
        "watermark": obs_counters.value("fleet.watermark", 0),
        "stragglers": obs_counters.value("fleet.stragglers", 0),
    }


def reset() -> None:
    """TEST-ONLY: close the shard writer and drop all cached state so
    the next use re-reads the environment."""
    global _run_id, _process_provider, _process, _watermark
    global _watermark_frozen, _writer, _writer_configured
    global _last_straggler_check
    _close_writer()
    with _writer_lock:
        _writer = None
        _writer_configured = False
    with _state_lock:
        _run_id = None
        _process_provider = None
        _process = None
        _watermark = 0
        _watermark_frozen = False
        _last_straggler_check = 0.0
