"""Consensus-game telemetry: a structured per-round event stream plus
live ``game.*`` counters/gauges.

Every other instrument in :mod:`bcg_tpu.obs` measures the *engine*
(spans, compile/retrace counters, HLO census, HBM ledger); this module
measures the *game* — the paper's actual subject.  When
``BCG_TPU_GAME_EVENTS=<path>`` is set, each :class:`~bcg_tpu.runtime.
orchestrator.BCGSimulation` gets a :class:`GameEventRecorder` that
emits one JSONL record per round event through the same bounded-queue /
writer-thread :class:`~bcg_tpu.obs.export.EventSink` idiom as
``BCG_TPU_SERVE_EVENTS`` — an emit never blocks the round loop, and a
full queue drops the OLDEST records counted in ``game.events_dropped``.
The file's first line is a run manifest (run id, schema version, flag
overrides, preset), so ``scripts/consensus_report.py`` can merge many
files from a sweep mechanically.

Record schema (``schema_version`` in the manifest; one JSON object per
line, every record carries ``ts`` + ``event`` + ``game`` + ``round``):

* ``game_start`` — per-game config: agents split, value range,
  threshold, max rounds, topology, seed, backend/model.
* ``round_start`` — round began.
* ``decision`` — one agent's decide-phase outcome: ``agent``, ``role``
  (``honest``/``byzantine``), ``value`` (None = abstain), ``outcome``
  (``valid`` / ``fallback`` = sequential-retry success / ``invalid`` =
  every attempt failed).
* ``deliveries`` — the topology-masked inbox of one agent for the
  round: ``agent``, ``senders`` (the proposals that actually arrived —
  ring/grid/custom masks and lossy channels show up here) and, when the
  exchange path records them, ``values`` (what this receiver saw from
  each sender — equivocation shows up as the same sender's value
  differing across receivers' records).
* ``vote`` — one agent's termination vote (``stop``/``continue``/
  ``abstain``).
* ``round_end`` — the :func:`~bcg_tpu.game.statistics.round_record`
  summary (same shape as saved ``rounds_data``) merged with
  :func:`~bcg_tpu.game.statistics.round_convergence` (distinct honest
  values, value spread, margin vs threshold, byzantine influence) and
  ``duration_ms``.
* ``game_end`` — converged?, rounds, termination reason, cumulative
  byzantine influence.

Live metrics (registered ONLY while a recorder exists — the
disabled-by-default path adds no counters, no threads): counters
``game.rounds`` / ``game.rounds.consensus`` / ``game.decisions`` /
``game.decisions.invalid`` / ``game.decisions.fallback`` /
``game.votes.stop`` / ``game.votes.continue`` / ``game.votes.abstain``
/ ``game.deliveries`` / ``game.byzantine.adoptions`` / ``game.games``
/ ``game.games.completed`` / ``game.games.converged``; gauges
``game.distinct_honest_values`` / ``game.value_spread`` /
``game.margin_vs_threshold``; histogram ``game.round_ms``.  All are
visible on the Prometheus endpoint (``BCG_TPU_METRICS_PORT``) mid-run.

No jax import — loadable by flag-only consumers.
"""

from __future__ import annotations

import atexit
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from bcg_tpu.game.statistics import round_convergence, round_record
from bcg_tpu.obs import (
    counters as obs_counters,
    export as obs_export,
    fleet as obs_fleet,
)
from bcg_tpu.runtime import envflags

# Round wall-time bucket bounds (ms): FakeEngine rounds run ~1-50 ms;
# real TPU rounds span hundreds of ms (warm decode) to tens of seconds
# (cold compile) — the top bound keeps p99 resolvable either way.
_ROUND_MS_BUCKETS = (5, 10, 25, 50, 100, 250, 1000, 5000, 30000)

_sink_lock = threading.Lock()
_sink: Optional[obs_export.EventSink] = None
_sink_configured = False


def _ensure_sink(preset: Optional[str] = None) -> Optional[obs_export.EventSink]:
    """The process-wide game-event sink (None when
    ``BCG_TPU_GAME_EVENTS`` is unset).  Created once, on the first
    recorder; the manifest header carries the creating game's preset."""
    global _sink, _sink_configured
    if _sink_configured:
        return _sink
    with _sink_lock:
        if not _sink_configured:
            path = envflags.get_str("BCG_TPU_GAME_EVENTS")
            if path:
                _sink = obs_export.EventSink(
                    path,
                    drop_counter="game.events_dropped",
                    manifest=obs_export.run_manifest(
                        kind="game", preset=preset
                    ),
                )
                # Drain on normal interpreter exit (daemon writer thread).
                atexit.register(reset_sink)
            _sink_configured = True
    return _sink


def reset_sink() -> None:
    """Drop the cached sink + its read-once flag — TEST-ONLY (and the
    atexit drain)."""
    global _sink, _sink_configured
    with _sink_lock:
        if _sink is not None:
            _sink.close()
        _sink = None
        _sink_configured = False


# Cross-game aggregate behind bench.py's game_stats attachment — the
# serving LAST_SERVE_STATS idiom for game telemetry.
_agg_lock = threading.Lock()
_agg = {
    "games": 0,
    "games_completed": 0,
    "games_converged": 0,
    "rounds": 0,
    "byzantine_adoptions": 0,
}


def summary() -> Optional[Dict[str, Any]]:
    """Cumulative game-telemetry summary for this process, or None when
    no recorder ever ran (bench attaches this on success AND error)."""
    with _agg_lock:
        if not _agg["games"]:
            return None
        out = dict(_agg)
    out["events_dropped"] = obs_counters.value("game.events_dropped")
    return out


def _reset_aggregate() -> None:
    """TEST-ONLY: zero the cross-game aggregate."""
    with _agg_lock:
        for k in _agg:
            _agg[k] = 0


def maybe_recorder(sim) -> Optional["GameEventRecorder"]:
    """A recorder for ``sim`` (a BCGSimulation) when
    ``BCG_TPU_GAME_EVENTS`` is set; None otherwise.  The None path is
    the whole disabled story: no sink, no thread, no ``game.*``
    registry entries, and the orchestrator's only cost is one
    ``is not None`` per emission site."""
    if not envflags.get_str("BCG_TPU_GAME_EVENTS"):
        return None
    return GameEventRecorder(sim)


class GameEventRecorder:
    """Per-simulation emitter of game events + live ``game.*`` metrics.

    Construction emits ``game_start`` and publishes the aggregate; the
    orchestrator calls the event methods from its round loop — each is
    a dict build + bounded-queue append (the sink's writer thread owns
    disk latency).
    """

    def __init__(self, sim):
        cfg = sim.config
        self._game_id = f"{sim.run_number}_g{sim._sim_uid}"
        # Sweep-tier job identity: rides game_start/game_end so resume
        # logic and report merges can key on the JOB (stable across
        # processes) instead of the per-process game id.
        self._job_id = getattr(sim, "sweep_job_id", None)
        self._threshold = float(sim.game.consensus_threshold)
        self._honest_ids = tuple(
            aid for aid, st in sim.game.agents.items() if not st.is_byzantine
        )
        self._byz_ids = tuple(
            aid for aid, st in sim.game.agents.items() if st.is_byzantine
        )
        # Game-only runs (FakeEngine, no serve layer) never pass the
        # engine/scheduler boot sites that start the metrics endpoint or
        # the fleet metric-shard flusher — kick both idempotent starters
        # here, BEFORE the sink exists, so the run manifest can carry
        # the rank's actual bound metrics_port and game.* metrics are
        # scrapeable/shardable mid-run.
        obs_export.maybe_start_http_server()
        obs_fleet.maybe_start_shard_writer()
        self._sink = _ensure_sink(preset=cfg.engine.model_name)
        self._round_t0: Optional[float] = None
        # Previous round's per-agent values + byzantine proposals — the
        # byzantine_influence inputs (adoption is measured against what
        # the adversary BROADCAST last round).
        self._prev_values: Dict[str, Any] = {
            aid: st.current_value for aid, st in sim.game.agents.items()
        }
        self._prev_byz_proposals: List[int] = []
        self._influence_total = 0
        self._ended = False
        self._round_hist = obs_counters.histogram(
            "game.round_ms", _ROUND_MS_BUCKETS
        )
        obs_counters.inc("game.games")
        with _agg_lock:
            _agg["games"] += 1
        job_field = {"job": self._job_id} if self._job_id else {}
        self._emit(
            "game_start",
            round=None,
            **job_field,
            num_honest=sim.game.num_honest,
            num_byzantine=sim.game.num_byzantine,
            value_range=list(sim.game.value_range),
            consensus_threshold=self._threshold,
            max_rounds=sim.game.max_rounds,
            topology=cfg.network.topology_type,
            seed=cfg.game.seed,
            backend=cfg.engine.backend,
            model=cfg.engine.model_name,
            strategy=cfg.game.byzantine_strategy,
            awareness=cfg.game.byzantine_awareness,
        )
        self._publish()

    def resync(self, sim) -> None:
        """Re-anchor on a REPLACED game object (checkpoint resume swaps
        ``sim.game`` after construction, with its own Byzantine
        assignment): refresh the role partition, threshold, and the
        previous-round influence reference — without emitting a second
        ``game_start`` or double-counting the game."""
        game = sim.game
        self._threshold = float(game.consensus_threshold)
        self._honest_ids = tuple(
            aid for aid, st in game.agents.items() if not st.is_byzantine
        )
        self._byz_ids = tuple(
            aid for aid, st in game.agents.items() if st.is_byzantine
        )
        if game.rounds:
            last = game.rounds[-1]
            self._prev_values = dict(last.agent_values)
            self._prev_byz_proposals = [
                int(last.agent_values[aid])
                for aid in self._byz_ids
                if last.agent_values.get(aid) is not None
            ]
        else:
            self._prev_values = {
                aid: st.current_value for aid, st in game.agents.items()
            }
            self._prev_byz_proposals = []

    # ------------------------------------------------------------ emission

    def _emit(self, event: str, **fields: Any) -> None:
        if self._sink is not None:
            self._sink.emit(event, game=self._game_id, **fields)

    def round_start(self, round_num: int) -> None:
        self._round_t0 = time.perf_counter()
        self._emit("round_start", round=round_num)

    def decision(self, round_num: int, agent_id: str, is_byzantine: bool,
                 value: Optional[int], outcome: str) -> None:
        """One agent's decide-phase result; ``outcome`` is ``valid`` /
        ``fallback`` (sequential-retry success) / ``invalid`` (all
        attempts failed -> abstain)."""
        obs_counters.inc("game.decisions")
        if outcome == "invalid":
            obs_counters.inc("game.decisions.invalid")
        elif outcome == "fallback":
            obs_counters.inc("game.decisions.fallback")
        self._emit(
            "decision", round=round_num, agent=agent_id,
            role="byzantine" if is_byzantine else "honest",
            value=value, outcome=outcome,
        )

    def deliveries(self, round_num: int, agent_id: str,
                   senders: Sequence[str],
                   values: Optional[Sequence[int]] = None) -> None:
        """The topology-masked inbox one agent actually received this
        round (one record per receiver, not per message — O(agents)
        lines per round, with the mask still fully reconstructable).
        ``values`` aligns with ``senders`` and records what THIS receiver
        saw from each — under an equivocating adversary the same sender's
        value differs across receivers, and this is the only record of
        that split (the report's equivocation tabulation reads it)."""
        obs_counters.inc("game.deliveries", len(senders))
        value_field = (
            {"values": [int(v) for v in values]} if values is not None else {}
        )
        self._emit(
            "deliveries", round=round_num, agent=agent_id,
            senders=list(senders), count=len(senders), **value_field,
        )

    def vote(self, round_num: int, agent_id: str, is_byzantine: bool,
             vote: Optional[bool]) -> None:
        label = "stop" if vote is True else (
            "continue" if vote is False else "abstain"
        )
        obs_counters.inc(f"game.votes.{label}")
        self._emit(
            "vote", round=round_num, agent=agent_id,
            role="byzantine" if is_byzantine else "honest", vote=label,
        )

    def round_end(self, round_num: int, game) -> None:
        """Emit the round summary + convergence metrics for the round
        the game just recorded (``game.rounds[-1]``), then roll the
        previous-round state forward and publish live gauges."""
        r = game.rounds[-1]
        conv = round_convergence(
            r,
            self._threshold,
            honest_ids=self._honest_ids,
            prev_values=self._prev_values,
            prev_byzantine_proposals=self._prev_byz_proposals,
        )
        duration_ms = (
            round((time.perf_counter() - self._round_t0) * 1e3, 3)
            if self._round_t0 is not None else None
        )
        if duration_ms is not None:
            self._round_hist.observe(duration_ms)
        self._influence_total += conv["byzantine_influence"]
        obs_counters.inc("game.rounds")
        if r.has_consensus:
            obs_counters.inc("game.rounds.consensus")
        if conv["byzantine_influence"]:
            obs_counters.inc(
                "game.byzantine.adoptions", conv["byzantine_influence"]
            )
        obs_counters.set_gauge(
            "game.distinct_honest_values", conv["distinct_honest_values"]
        )
        obs_counters.set_gauge("game.value_spread", conv["value_spread"])
        obs_counters.set_gauge(
            "game.margin_vs_threshold", conv["margin_vs_threshold"]
        )
        record = round_record(r, include_byzantine=bool(self._byz_ids))
        record.update(conv)
        self._emit("round_end", duration_ms=duration_ms, **record)
        # Roll forward: this round's values and byz proposals become the
        # next round's influence reference.
        self._prev_values = dict(r.agent_values)
        self._prev_byz_proposals = [
            int(r.agent_values[aid])
            for aid in self._byz_ids
            if r.agent_values.get(aid) is not None
        ]
        with _agg_lock:
            _agg["rounds"] += 1
            _agg["byzantine_adoptions"] += conv["byzantine_influence"]
        self._publish()

    def game_end(self, game) -> None:
        """Terminal record; idempotent (drivers may call run_round past
        game_over defensively)."""
        if self._ended:
            return
        self._ended = True
        obs_counters.inc("game.games.completed")
        if game.consensus_reached:
            obs_counters.inc("game.games.converged")
        job_field = {"job": self._job_id} if self._job_id else {}
        self._emit(
            "game_end",
            round=len(game.rounds),
            **job_field,
            converged=bool(game.consensus_reached),
            consensus_value=game.consensus_value,
            rounds=len(game.rounds),
            termination_reason=game.termination_reason,
            byzantine_influence=self._influence_total,
        )
        with _agg_lock:
            _agg["games_completed"] += 1
            if game.consensus_reached:
                _agg["games_converged"] += 1
        self._publish()

    @staticmethod
    def _publish() -> None:
        from bcg_tpu.runtime import metrics

        metrics.publish_game_stats(summary())
