"""Health & alerting plane: rule-driven evaluation over the metrics
registry, plus the process readiness/liveness state behind the metrics
HTTP server's ``/healthz`` and ``/readyz`` endpoints.

Six PRs of passive measurement made every failure mode *visible*;
nothing in the process *evaluated* it.  This module closes the loop:

* :class:`AlertRule` — a declarative rule over registry names, one of
  four kinds: ``threshold`` (gauge/counter level), ``delta_rate``
  (counter movement per evaluation window, trailing-``*`` wildcard
  sums a family), ``burn_rate`` (SLO violation fraction against an
  error budget, Google-SRE-style fast+slow dual windows), and
  ``staleness`` (epoch-ms heartbeat age and/or a value that stops
  moving).  Rules carry severity (``info``/``warn``/``page``) and a
  ``for_cycles`` debounce.
* :class:`AlertEngine` — evaluates the ruleset over ONE
  ``counters.snapshot()`` per cycle on a periodic daemon thread
  (``BCG_TPU_ALERT_MS``).  Firing->resolved transitions are deduped
  (an alert fires once per episode, re-fire after a resolve counts a
  flap), counted under the registered ``alert.*`` subsystem, exported
  as per-rule ``alert.firing.<rule>`` gauges (which the fleet shard
  plane carries across ranks) plus a labeled ``bcg_alert_firing``
  family on the Prometheus exposition, and emitted as manifest-headed
  JSONL through a bounded :class:`~bcg_tpu.obs.export.EventSink`
  (``BCG_TPU_ALERT_EVENTS``; drops counted in
  ``alert.events_dropped``; ``scripts/alert_report.py`` merges files).
* Readiness/health state — a push API (:func:`mark_ready` /
  :func:`mark_unready`) the serve scheduler drives at its lifecycle
  seams (boot, hang-watchdog window, EngineDead, close) plus pull
  probes (:func:`register_readiness_probe`) for conditions best read
  at request time (backpressure watermark).  Pushed transitions are
  recorded in a bounded history so "did readiness flip during the
  hang window" is checkable without polling.

Enablement follows the hostsync idiom: ``BCG_TPU_ALERTS`` is read
ONCE, on the first surface call; off means zero surface — no ``alert.*``
names registered, no evaluator thread, a byte-identical Prometheus
exposition.  The readiness state itself is plain module state (no
registry names, no threads) so ``/readyz`` serves the gateway PR even
with alerting off.

No jax import — loadable by flag-only consumers.
"""

from __future__ import annotations

import atexit
import re
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from bcg_tpu.obs import counters as obs_counters, export as obs_export
from bcg_tpu.obs import fleet as obs_fleet
from bcg_tpu.runtime import envflags

SEVERITIES = ("info", "warn", "page")
RULE_KINDS = ("threshold", "delta_rate", "burn_rate", "staleness")
_RULE_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")


@dataclass(frozen=True)
class AlertRule:
    """One declarative alert rule over registry names.

    Field use by kind:

    * ``threshold`` — fires while ``metric``'s current value is ``op``
      (``gt``/``lt``) ``value``.  An absent metric never fires (absence
      is the ``staleness`` kind's business).
    * ``delta_rate`` — fires when ``metric`` moved by more than
      ``value`` over the last evaluation window; a trailing ``*`` sums
      the matching family (``engine.retrace.*``).  ``unless_metric``
      (same wildcard syntax) suppresses the rule when THAT family also
      moved in the window — "injected without recovered" composites.
    * ``burn_rate`` — violation fraction ``delta(metric) /
      delta(requests_metric)`` over BOTH a fast (``fast_cycles``) and a
      slow (``slow_cycles``) window; fires while both fractions exceed
      ``budget * burn_factor`` and the denominator moved.  Early in a
      run the slow window clamps to "since engine start".
    * ``staleness`` — with ``max_age_ms`` > 0: fires while ``metric``
      is a nonzero epoch-ms gauge older than ``max_age_ms`` (heartbeat
      age).  With ``stall_cycles`` > 0: fires once the metric is
      present but unchanged for that many consecutive cycles
      (watermark stall).  Either arm trips the rule.

    ``for_cycles`` debounces: the condition must hold for that many
    ADDITIONAL consecutive cycles before the rule fires (0 = fire on
    the first true cycle).  Firing is an edge, not a level: one
    ``fired`` count + one JSONL record per episode; a re-fire after a
    resolve counts ``alert.flaps``.
    """

    name: str
    kind: str
    severity: str = "warn"
    summary: str = ""
    for_cycles: int = 0
    metric: str = ""
    op: str = "gt"
    value: float = 0.0
    unless_metric: str = ""
    requests_metric: str = ""
    budget: float = 0.0
    burn_factor: float = 1.0
    fast_cycles: int = 1
    slow_cycles: int = 5
    max_age_ms: float = 0.0
    stall_cycles: int = 0

    def __post_init__(self):
        if not _RULE_NAME_RE.match(self.name):
            raise ValueError(f"alert rule name {self.name!r} must match "
                             f"{_RULE_NAME_RE.pattern}")
        if self.kind not in RULE_KINDS:
            raise ValueError(f"alert rule {self.name}: unknown kind "
                             f"{self.kind!r} (one of {RULE_KINDS})")
        if self.severity not in SEVERITIES:
            raise ValueError(f"alert rule {self.name}: unknown severity "
                             f"{self.severity!r} (one of {SEVERITIES})")
        if self.op not in ("gt", "lt"):
            raise ValueError(f"alert rule {self.name}: op must be gt|lt")
        if self.kind == "staleness" and not (self.max_age_ms > 0
                                             or self.stall_cycles > 0):
            raise ValueError(f"alert rule {self.name}: staleness needs "
                             "max_age_ms and/or stall_cycles")


def build_default_rules() -> List[AlertRule]:
    """The stock ruleset: one rule per known failure mode the existing
    observability planes measure but nothing evaluated.  Severity
    ``page`` feeds the ``/healthz`` verdict; ``warn`` is the
    dashboards-and-timeline tier."""
    return [
        AlertRule(
            name="slo_burn", kind="burn_rate", severity="page",
            metric="serve.slo.violations", requests_metric="serve.requests",
            budget=0.05, burn_factor=2.0, fast_cycles=1, slow_cycles=5,
            summary="SLO violation fraction burning >2x the 5% error "
                    "budget in both fast and slow windows",
        ),
        AlertRule(
            name="engine_errors", kind="delta_rate", severity="page",
            metric="serve.engine_errors",
            summary="engine call failures in the evaluation window",
        ),
        AlertRule(
            name="engine_rebuilt", kind="delta_rate", severity="warn",
            metric="serve.engine_rebuilds",
            summary="hang-watchdog rebuilt the engine (recovery activity)",
        ),
        AlertRule(
            name="dispatch_retries", kind="delta_rate", severity="warn",
            metric="serve.dispatch_retries",
            summary="dispatch retry ladder engaged (recovery activity)",
        ),
        AlertRule(
            name="events_dropped", kind="threshold", severity="warn",
            metric="serve.events_dropped", op="gt", value=0,
            summary="lifecycle event sink dropped records (queue "
                    "overflow or dead disk)",
        ),
        AlertRule(
            name="retrace_storm", kind="delta_rate", severity="warn",
            metric="engine.retrace.*", for_cycles=1,
            summary="steady-state retraces: jit cache misses after warmup",
        ),
        AlertRule(
            name="hbm_unaccounted", kind="threshold", severity="warn",
            metric="hbm.unaccounted_bytes", op="gt", value=64 * 2 ** 20,
            summary="allocator-vs-ledger drift above 64 MiB (leak or "
                    "unledgered buffer)",
        ),
        AlertRule(
            name="pool_headroom", kind="threshold", severity="warn",
            metric="kvpool.headroom_bytes", op="lt", value=1,
            summary="paged-KV free-block headroom exhausted",
        ),
        AlertRule(
            name="heartbeat_stale", kind="staleness", severity="page",
            metric="fleet.heartbeat_ms", max_age_ms=15000.0,
            summary="fleet heartbeat older than 15s",
        ),
        AlertRule(
            name="watermark_stall", kind="staleness", severity="warn",
            metric="fleet.watermark", stall_cycles=30,
            summary="shard watermark unchanged for 30 evaluation cycles",
        ),
        AlertRule(
            name="fleet_straggler", kind="threshold", severity="warn",
            metric="fleet.stragglers", op="gt", value=0,
            summary="fleet straggler verdict (lagging watermark or "
                    "stale heartbeat)",
        ),
        AlertRule(
            name="chaos_unrecovered", kind="delta_rate", severity="page",
            metric="chaos.injected", unless_metric="serve.recoveries",
            summary="chaos faults injected with no recovery activity "
                    "in the same window",
        ),
    ]


class _RuleState:
    __slots__ = ("consecutive_true", "firing", "fired_count",
                 "stall_count", "last_sample")

    def __init__(self):
        self.consecutive_true = 0
        self.firing = False
        self.fired_count = 0
        self.stall_count = 0
        self.last_sample: Optional[float] = None


class AlertEngine:
    """Evaluates a ruleset over ONE registry snapshot per cycle.

    All ``alert.*`` registry names are created at CONSTRUCTION, not on
    first transition — an enabled-but-quiet process still advertises
    the alerting surface, and the exact-bytes zero-surface test has a
    definite complement to pin."""

    def __init__(self, rules: Optional[List[AlertRule]] = None,
                 period_ms: Optional[int] = None):
        self.rules = list(rules) if rules is not None else build_default_rules()
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate alert rule names: {sorted(names)}")
        self.period_ms = (envflags.get_int("BCG_TPU_ALERT_MS")
                          if period_ms is None else period_ms)
        obs_counters.counter("alert.evaluations")
        obs_counters.counter("alert.fired")
        obs_counters.counter("alert.resolved")
        obs_counters.counter("alert.flaps")
        obs_counters.counter("alert.events_dropped")
        obs_counters.set_gauge("alert.rules", len(self.rules))
        for r in self.rules:
            obs_counters.set_gauge(f"alert.firing.{r.name}", 0)
        self._states = {r.name: _RuleState() for r in self.rules}
        self._lock = threading.Lock()
        # Recent snapshots, newest last; sized for the largest burn-rate
        # slow window (+1 so a k-cycle delta has a base to diff against).
        depth = max([r.slow_cycles for r in self.rules
                     if r.kind == "burn_rate"] + [1]) + 1
        self._history: "deque" = deque(maxlen=depth)
        self.evaluations = 0
        self.fired = 0
        self.resolved = 0
        self.flaps = 0
        self._sink: Optional[obs_export.EventSink] = None
        path = envflags.get_str("BCG_TPU_ALERT_EVENTS")
        if path:
            self._sink = obs_export.EventSink(
                path, drop_counter="alert.events_dropped",
                manifest=obs_export.run_manifest(kind="alert"),
            )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        with self._lock:
            if self._thread is not None:
                return
            self._thread = threading.Thread(
                target=self._run, name="bcg-alert-eval", daemon=True
            )
            self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.period_ms / 1000.0):
            self.evaluate_once()
            self.publish()

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            doomed, self._thread = self._thread, None
        if doomed is not None:
            doomed.join(timeout=10.0)
        if self._sink is not None:
            self._sink.close()
            self._sink = None

    # ----------------------------------------------------------- evaluation
    def evaluate_once(self, now_ms: Optional[float] = None) -> None:
        """One evaluation cycle over one snapshot.  Also the seam the
        straggler plane rides: a fleet-enabled process gets its
        (rate-limited) ``check_stragglers`` verdict refreshed here, so
        the ``fleet_straggler`` rule alerts on it instead of the gauge
        waiting for a reader."""
        if obs_fleet.enabled():
            obs_fleet.check_stragglers()
        if now_ms is None:
            # Heartbeat gauges are epoch-ms BY CONTRACT (cross-process
            # comparisons) — age must diff against the same clock.
            now_ms = time.time() * 1e3
        with self._lock:
            snap = obs_counters.snapshot()
            self._history.append(snap)
            self.evaluations += 1
            obs_counters.inc("alert.evaluations")
            for rule in self.rules:
                cond, measured = self._check(rule, snap, now_ms)
                st = self._states[rule.name]
                st.consecutive_true = st.consecutive_true + 1 if cond else 0
                if st.consecutive_true > rule.for_cycles and not st.firing:
                    st.firing = True
                    if st.fired_count:
                        self.flaps += 1
                        obs_counters.inc("alert.flaps")
                    st.fired_count += 1
                    self.fired += 1
                    obs_counters.inc("alert.fired")
                    obs_counters.set_gauge(f"alert.firing.{rule.name}", 1)
                    self._emit("firing", rule, measured)
                elif not cond and st.firing:
                    st.firing = False
                    self.resolved += 1
                    obs_counters.inc("alert.resolved")
                    obs_counters.set_gauge(f"alert.firing.{rule.name}", 0)
                    self._emit("resolved", rule, measured)

    @staticmethod
    def _sample(snap: Dict[str, float], pattern: str
                ) -> Tuple[bool, float]:
        """(present, value) of a metric — a trailing ``*`` sums the
        matching family (present when any member exists)."""
        if pattern.endswith("*"):
            prefix = pattern[:-1]
            hits = [v for k, v in snap.items() if k.startswith(prefix)]
            return bool(hits), float(sum(hits))
        if pattern in snap:
            return True, float(snap[pattern])
        return False, 0.0

    def _delta(self, pattern: str, cycles: int) -> Tuple[bool, float]:
        """Movement of a metric over the last ``cycles`` evaluation
        windows (clamped to history depth).  The FIRST cycle has no
        base snapshot, so nothing "moves" — pre-engine counts can't
        fire a rate rule at boot."""
        if len(self._history) < 2:
            return False, 0.0
        base_idx = max(0, len(self._history) - 1 - cycles)
        _, cur = self._sample(self._history[-1], pattern)
        _, base = self._sample(self._history[base_idx], pattern)
        return True, cur - base

    def _check(self, rule: AlertRule, snap: Dict[str, float],
               now_ms: float) -> Tuple[bool, float]:
        if rule.kind == "threshold":
            present, v = self._sample(snap, rule.metric)
            if not present:
                return False, 0.0
            cond = v > rule.value if rule.op == "gt" else v < rule.value
            return cond, v
        if rule.kind == "delta_rate":
            ok, d = self._delta(rule.metric, 1)
            if not ok or d <= rule.value:
                return False, d
            if rule.unless_metric:
                _, ud = self._delta(rule.unless_metric, 1)
                if ud > 0:
                    return False, d
            return True, d
        if rule.kind == "burn_rate":
            ok_f, viol_f = self._delta(rule.metric, rule.fast_cycles)
            _, req_f = self._delta(rule.requests_metric, rule.fast_cycles)
            _, viol_s = self._delta(rule.metric, rule.slow_cycles)
            _, req_s = self._delta(rule.requests_metric, rule.slow_cycles)
            if not ok_f or req_f <= 0 or req_s <= 0:
                return False, 0.0
            burn = rule.budget * rule.burn_factor
            frac_f, frac_s = viol_f / req_f, viol_s / req_s
            return (frac_f > burn and frac_s > burn), frac_f
        # staleness
        st = self._states[rule.name]
        present, v = self._sample(snap, rule.metric)
        stale = False
        measured = 0.0
        if present and rule.max_age_ms > 0 and v > 0:
            age_ms = now_ms - v  # lint: ignore[BCG-TIME-WALL]
            measured = age_ms
            stale = age_ms > rule.max_age_ms
        if rule.stall_cycles > 0:
            if present and st.last_sample is not None and v == st.last_sample:
                st.stall_count += 1
            else:
                st.stall_count = 0
            st.last_sample = v if present else None
            if st.stall_count >= rule.stall_cycles:
                stale = True
                measured = float(st.stall_count)
        return stale, measured

    def _emit(self, state: str, rule: AlertRule, measured: float) -> None:
        if self._sink is not None:
            self._sink.emit(
                "alert", rule=rule.name, severity=rule.severity,
                state=state, kind=rule.kind, value=round(measured, 6),
                summary=rule.summary,
            )

    # ------------------------------------------------------------ inspection
    def firing(self) -> List[str]:
        with self._lock:
            return sorted(n for n, st in self._states.items() if st.firing)

    def page_firing(self) -> List[str]:
        sev = {r.name: r.severity for r in self.rules}
        return [n for n in self.firing() if sev[n] == "page"]

    def fired_by_rule(self) -> Dict[str, int]:
        """Episode counts per rule name (fired-at-least-once rules
        only) — the perf gate's 'expected rules actually fired' oracle."""
        with self._lock:
            return {n: st.fired_count for n, st in self._states.items()
                    if st.fired_count}

    def summary(self) -> Dict[str, Any]:
        firing = self.firing()
        sev = {r.name: r.severity for r in self.rules}
        return {
            "enabled": True,
            "period_ms": self.period_ms,
            "rules": len(self.rules),
            "evaluations": self.evaluations,
            "fired": self.fired,
            "resolved": self.resolved,
            "flaps": self.flaps,
            "firing": firing,
            "page_firing": [n for n in firing if sev[n] == "page"],
            "fired_by_rule": self.fired_by_rule(),
        }

    def publish(self) -> None:
        from bcg_tpu.runtime import metrics

        metrics.publish_alerts(self.summary())


# --------------------------------------------------------- module surface
_config_lock = threading.Lock()
_engine: Optional[AlertEngine] = None
_configured = False


def _firing_blocks(labels: str) -> List[Tuple[str, List[str]]]:
    """Extra Prometheus exposition blocks: the labeled
    ``bcg_alert_firing{rule="..."}`` family, one sample per rule (0
    when quiet — a scraper sees the full rule catalog, not just
    incidents).  Installed as the export module's extra-blocks
    provider only while an engine is live, so the alerts-off
    exposition stays byte-identical."""
    eng = _engine
    if eng is None:
        return []
    firing = set(eng.firing())
    metric = "bcg_alert_firing"
    lines = [
        f"# HELP {metric} bcg_tpu alert rule firing state (1=firing)",
        f"# TYPE {metric} gauge",
    ]
    for rule in eng.rules:
        body = f'{labels},rule="{rule.name}"' if labels else f'rule="{rule.name}"'
        lines.append(
            f"{metric}{{{body}}} {1 if rule.name in firing else 0}"
        )
    return [(metric, lines)]


def _ensure() -> Optional[AlertEngine]:
    global _engine, _configured
    if _configured:
        return _engine
    with _config_lock:
        if not _configured:
            if envflags.get_bool("BCG_TPU_ALERTS"):
                eng = AlertEngine()
                obs_export.set_extra_blocks_provider(_firing_blocks)
                eng.start()
                _engine = eng
                # Drain the JSONL tail on normal interpreter exit —
                # the evaluator is a daemon thread.
                atexit.register(reset)
            _configured = True
    return _engine


def maybe_start() -> Optional[AlertEngine]:
    """Read ``BCG_TPU_ALERTS`` once and start the evaluator when set.
    Called from scheduler boot — cheap no-op on every later call (and
    with the flag unset: zero surface, see module docstring)."""
    return _ensure()


def engine() -> Optional[AlertEngine]:
    return _engine if _configured else _ensure()


def enabled() -> bool:
    return engine() is not None


def evaluate_now() -> None:
    """Force one evaluation cycle synchronously (gates and tests drive
    deterministic cycles this way; the periodic thread stays the
    production cadence)."""
    e = engine()
    if e is not None:
        e.evaluate_once()
        e.publish()


def summary() -> Optional[Dict[str, Any]]:
    e = engine()
    return e.summary() if e is not None else None


def reset() -> None:
    """Stop the engine and drop the read-once cache — TEST-ONLY (also
    the atexit drain hook).  Registered ``alert.*`` names persist in
    the in-process registry (registries don't unregister); the
    zero-surface pin runs in a subprocess for exactly this reason."""
    global _engine, _configured
    with _config_lock:
        doomed, _engine = _engine, None
        _configured = False
    # stop() joins the evaluator thread — dispatch it OUTSIDE
    # _config_lock so a slow drain can never wedge configuration.
    if doomed is not None:
        obs_export.set_extra_blocks_provider(None)
        doomed.stop()


# ------------------------------------------------- readiness / health state
# Plain module state, deliberately independent of BCG_TPU_ALERTS: the
# gateway consumes /readyz whether or not alert evaluation is on, and
# keeping it registry-free preserves the zero-surface exposition pin.
_health_lock = threading.Lock()
_unready: Dict[str, str] = {}
_probes: Dict[str, Callable[[], Optional[str]]] = {}
_transitions: "deque" = deque(maxlen=256)
_last_recorded: Optional[Tuple[bool, Tuple[Tuple[str, str], ...]]] = None


def _record_locked() -> None:
    ready = not _unready
    key = (ready, tuple(sorted(_unready.items())))
    global _last_recorded
    if key == _last_recorded:
        return
    _last_recorded = key
    _transitions.append({
        "ts": time.time(),  # epoch by contract: merged across ranks
        "ready": ready,
        "reasons": dict(_unready),
    })


def mark_ready(component: str) -> None:
    """Push: ``component`` no longer objects to readiness."""
    with _health_lock:
        _unready.pop(component, None)
        _record_locked()


def mark_unready(component: str, reason: str) -> None:
    """Push: ``component`` vetoes readiness (hang window, EngineDead,
    scheduler closed).  Recorded in the bounded transition history."""
    with _health_lock:
        _unready[component] = reason
        _record_locked()


def register_readiness_probe(component: str,
                             probe: Callable[[], Optional[str]]) -> None:
    """Pull: ``probe`` is called at each readiness READ and returns a
    veto reason or None — for conditions best sampled at request time
    (backpressure watermark) rather than evented."""
    with _health_lock:
        _probes[component] = probe


def clear_readiness(*components: str) -> None:
    """Drop pushed state and probes for ``components`` (scheduler
    close unhooks itself so the next boot starts clean)."""
    with _health_lock:
        for c in components:
            _unready.pop(c, None)
            _probes.pop(c, None)
        _record_locked()


def readiness() -> Tuple[bool, Dict[str, Any]]:
    """``/readyz`` verdict: ready iff no component vetoes — pushed
    state (hang window, EngineDead, closed) merged with live probe
    reads (backpressure)."""
    with _health_lock:
        reasons = dict(_unready)
        probes = list(_probes.items())
    for name, probe in probes:  # probes read scheduler attrs; never under our lock
        why = probe()
        if why:
            reasons[name] = why
    ready = not reasons
    return ready, {
        "status": "ready" if ready else "unready",
        "reasons": reasons,
    }


def readiness_history() -> List[Dict[str, Any]]:
    """The bounded pushed-transition log (newest last) — lets a gate
    assert "readiness flipped during the hang window and back" without
    having to poll inside it."""
    with _health_lock:
        return list(_transitions)


def health() -> Tuple[bool, Dict[str, Any]]:
    """``/healthz`` verdict: the process is up (trivially, it
    answered) and no page-severity alert is firing.  With alerting off
    the second clause is vacuously true."""
    e = engine()
    pages = e.page_firing() if e is not None else []
    ok = not pages
    return ok, {
        "status": "ok" if ok else "failing",
        "page_firing": pages,
    }


def reset_readiness() -> None:
    """Clear pushed state, probes, and the transition history —
    TEST-ONLY."""
    global _last_recorded
    with _health_lock:
        _unready.clear()
        _probes.clear()
        _transitions.clear()
        _last_recorded = None
