"""HBM ledger: live per-device byte accounting of what the engine holds.

The allocator's ``bytes_in_use`` says how much HBM is gone but never
WHAT it is; the engine's budget math (``_kv_row_budget`` /
``cap_for``) models what SHOULD fit but records nothing at runtime.
The ledger is the missing middle: every long-lived device allocation
the serving stack makes is charged to a named account when it
materializes and credited back when it is released, so at any instant
``snapshot()`` decomposes device memory into params / decode-KV slabs /
prefix-cache entries / speculative decode-slot over-allocation — the
accounting substrate ROADMAP item 1's paged-KV work will assert its
superlinear-win claims against.

Accounts are keyed: ``charge(account, key, nbytes)`` is idempotent per
key (re-charging a key replaces its amount — a re-used cache shape does
not double-count) and ``credit(account, key)`` of an unknown key is a
no-op (eviction paths may race shutdown).  All amounts are PER-DEVICE
bytes — callers compute them through
``parallel/sharding.tree_bytes_per_device`` /
``kv_cache_bytes_per_device`` so the ledger and the engine's admission
math cannot drift apart.

Every mutation republishes gauges (``hbm.<account>_bytes``,
``hbm.total_bytes``, and — when a device limit was declared —
``hbm.limit_bytes`` / ``hbm.headroom_bytes``) into the process-wide
counter registry, so the ledger rides bench JSON, serve stats, and the
Prometheus exposition with no extra plumbing.  :func:`reconcile`
compares the ledger total against the allocator's actual reading
(``runtime/metrics._device_memory``) when real devices are present —
the drift gauge (``hbm.unaccounted_bytes``) is what flags a leak or an
unledgered allocation class.

No jax import — loadable by flag-only consumers.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from bcg_tpu.obs import counters as obs_counters

# Published accounts, in render order.  "spec_slots" is the decode-tail
# OVER-allocation of the speculative/fast-forward loops (cache slots
# past max_new+1) — carved out of the kv_cache charge by the engine so
# the cost of speculation's K+1 verify window is first-class.
ACCOUNTS = ("params", "kv_cache", "prefix_cache", "spec_slots")


class HbmLedger:
    """Keyed per-account byte ledger; one process-wide instance
    (:data:`LEDGER`) mirrors itself into registry gauges."""

    def __init__(self, publish: bool = True):
        self._lock = threading.Lock()
        self._entries: Dict[str, Dict[object, int]] = {a: {} for a in ACCOUNTS}
        self._limit: Optional[int] = None
        self._publish = publish

    # ------------------------------------------------------------- mutation

    def set_limit(self, limit_bytes: Optional[int]) -> None:
        """Declare the per-device capacity (engine boot; None on CPU —
        headroom then stays unpublished rather than lying)."""
        with self._lock:
            self._limit = limit_bytes
        self._republish()

    def charge(self, account: str, key: object, nbytes: int) -> None:
        if account not in self._entries:
            raise KeyError(
                f"unknown ledger account {account!r}; known: {ACCOUNTS}"
            )
        with self._lock:
            self._entries[account][key] = int(nbytes)
        self._republish()

    def credit(self, account: str, key: object) -> None:
        if account not in self._entries:
            raise KeyError(
                f"unknown ledger account {account!r}; known: {ACCOUNTS}"
            )
        with self._lock:
            self._entries[account].pop(key, None)
        self._republish()

    def credit_all(self, account: str) -> None:
        """Drop every key of one account (engine shutdown)."""
        with self._lock:
            self._entries[account].clear()
        self._republish()

    def reset(self) -> None:
        """Full wipe — TEST-ONLY (live engines hold charged keys)."""
        with self._lock:
            for account in self._entries.values():
                account.clear()
            self._limit = None
        self._republish()

    # -------------------------------------------------------------- reading

    def total(self, account: Optional[str] = None) -> int:
        with self._lock:
            if account is not None:
                return sum(self._entries[account].values())
            return sum(
                sum(keys.values()) for keys in self._entries.values()
            )

    def headroom(self) -> Optional[int]:
        """Per-device bytes the declared limit still affords, or None
        when no limit was declared (CPU)."""
        with self._lock:
            if self._limit is None:
                return None
            used = sum(sum(keys.values()) for keys in self._entries.values())
            return self._limit - used

    def snapshot(self) -> Dict[str, Optional[int]]:
        """Flat dict for bench JSON / serve stats: per-account bytes,
        total, limit and headroom (absent-limit entries are None)."""
        with self._lock:
            out: Dict[str, Optional[int]] = {
                f"{a}_bytes": sum(keys.values())
                for a, keys in self._entries.items()
            }
            total = sum(v for v in out.values() if v)
            out["total_bytes"] = total
            out["limit_bytes"] = self._limit
            out["headroom_bytes"] = (
                self._limit - total if self._limit is not None else None
            )
        return out

    def reconcile(self) -> Dict[str, Optional[int]]:
        """Compare the ledger against the allocator's actual per-device
        reading (max across devices, ``runtime/metrics._device_memory``).
        Publishes ``hbm.device_bytes_in_use`` and
        ``hbm.unaccounted_bytes`` (allocator minus ledger; transient
        workspace and XLA temp buffers land here) when the backend
        exposes allocator stats; on CPU returns the ledger view with
        both set to None."""
        from bcg_tpu.runtime.metrics import _device_memory

        in_use, _peak = _device_memory()
        snap = self.snapshot()
        snap["device_bytes_in_use"] = in_use
        snap["unaccounted_bytes"] = (
            in_use - snap["total_bytes"] if in_use is not None else None
        )
        if self._publish and in_use is not None:
            obs_counters.set_gauge("hbm.device_bytes_in_use", in_use)
            obs_counters.set_gauge(
                "hbm.unaccounted_bytes", snap["unaccounted_bytes"]
            )
        return snap

    # ------------------------------------------------------------ publishing

    def _republish(self) -> None:
        if not self._publish:
            return
        snap = self.snapshot()
        for account in ACCOUNTS:
            obs_counters.set_gauge(
                f"hbm.{account}_bytes", snap[f"{account}_bytes"] or 0
            )
        obs_counters.set_gauge("hbm.total_bytes", snap["total_bytes"])
        if snap["limit_bytes"] is not None:
            obs_counters.set_gauge("hbm.limit_bytes", snap["limit_bytes"])
            obs_counters.set_gauge("hbm.headroom_bytes", snap["headroom_bytes"])


# The single process-wide ledger (mirrors the REGISTRY idiom).
LEDGER = HbmLedger()


def charge(account: str, key: object, nbytes: int) -> None:
    LEDGER.charge(account, key, nbytes)


def credit(account: str, key: object) -> None:
    LEDGER.credit(account, key)


def credit_all(account: str) -> None:
    LEDGER.credit_all(account)


def set_limit(limit_bytes: Optional[int]) -> None:
    LEDGER.set_limit(limit_bytes)


def snapshot() -> Dict[str, Optional[int]]:
    return LEDGER.snapshot()


def reconcile() -> Dict[str, Optional[int]]:
    return LEDGER.reconcile()


def headroom() -> Optional[int]:
    return LEDGER.headroom()


def reset() -> None:
    LEDGER.reset()
