"""Telemetry export: Prometheus text exposition, request-lifecycle
JSONL sink, and an optional stdlib HTTP ``/metrics`` endpoint.

Three consumers of the same registry, one module:

* :func:`render_prometheus` — the counter/gauge registry in Prometheus
  text-exposition format (v0.0.4): dotted registry names become
  ``bcg_``-prefixed underscore names, counters carry the ``_total``
  suffix and ``# TYPE ... counter``, gauges ``# TYPE ... gauge``, and
  every metric's HELP line cites the original dotted name (the registry
  name IS the documentation key in DESIGN.md's taxonomy).  Escaping
  follows the exposition spec (backslash and newline in HELP text).
* :class:`EventSink` — an append-only JSONL stream of serve-path
  request lifecycle events (``admitted`` / ``rejected`` / ``cancelled``
  / ``dispatched`` / ``completed`` / ``failed``), each line carrying
  the request id, row count, and the latency breakdown the scheduler
  already measures.  Enabled by ``BCG_TPU_SERVE_EVENTS=<path>``; the
  scheduler calls :func:`emit_event`, which is a no-op when disabled.
* :func:`maybe_start_http_server` — a daemon-thread
  ``ThreadingHTTPServer`` serving ``GET /metrics`` with the live
  exposition, gated by ``BCG_TPU_METRICS_PORT`` (0 = off, the
  default).  Idempotent per process; a bind failure warns and stays
  off rather than taking the engine down.  This is the piece a
  deployment's Prometheus scrapes.

No jax import — loadable by flag-only consumers.
"""

from __future__ import annotations

import atexit
import json
import os
import re
import threading
import time
from collections import deque
from typing import Any, Dict, Optional, Tuple

from bcg_tpu.obs import counters as obs_counters, fleet as obs_fleet
from bcg_tpu.runtime import envflags, resilience

_NAME_PREFIX = "bcg_"
_INVALID_CHARS = re.compile(r"[^a-zA-Z0-9_:]")

# Optional extra exposition blocks (labeled sample families the
# label-free registry can't carry, e.g. the alert plane's
# bcg_alert_firing{rule=...}).  None — the default, and the alerts-off
# state — keeps render_prometheus byte-identical to the provider-free
# form; bcg_tpu/obs/alerts.py installs its provider only while an
# engine is live.
_extra_blocks_provider = None
_provider_lock = threading.Lock()


def set_extra_blocks_provider(provider) -> None:
    """Install (or, with None, remove) a ``labels -> [(metric_name,
    [exposition lines])]`` callback merged into every rendered
    exposition."""
    global _extra_blocks_provider
    with _provider_lock:
        _extra_blocks_provider = provider


def prometheus_name(registry_name: str, counter: bool = False) -> str:
    """Dotted registry name -> Prometheus metric name
    (``serve.linger_le_1ms`` -> ``bcg_serve_linger_le_1ms``; counters
    get the conventional ``_total`` suffix)."""
    name = _NAME_PREFIX + _INVALID_CHARS.sub("_", registry_name.replace(".", "_"))
    if counter and not name.endswith("_total"):
        name += "_total"
    return name


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _format_value(value) -> str:
    # Prometheus values are floats; render integers without the
    # trailing .0 noise (both parse identically).
    f = float(value)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def render_prometheus(typed: Optional[Dict[str, Dict[str, Any]]] = None,
                      labels: Optional[str] = None) -> str:
    """The registry (or an explicit ``snapshot_typed()``-shaped dict) in
    Prometheus text-exposition format, sorted by metric name.

    Histograms render as the conformant family the spec requires:
    cumulative ``<name>_bucket{le="..."}`` samples over the declared
    bounds plus the mandatory ``le="+Inf"`` bucket (== ``_count``),
    then ``<name>_sum`` and ``<name>_count``.

    ``labels`` is a pre-escaped label body (``process="3",host="a"``)
    applied to every sample; None resolves the fleet identity labels
    (:func:`bcg_tpu.obs.fleet.prom_label_body`) — the empty string when
    fleet stamping is off, keeping the exposition byte-identical to the
    unstamped form."""
    if typed is None:
        typed = obs_counters.snapshot_typed()
    if labels is None:
        labels = obs_fleet.prom_label_body()
    wrap = f"{{{labels}}}" if labels else ""
    rows = [
        (prometheus_name(name, counter=True), name, "counter", value)
        for name, value in typed.get("counters", {}).items()
    ] + [
        (prometheus_name(name), name, "gauge", value)
        for name, value in typed.get("gauges", {}).items()
    ]
    # Histogram buckets merge the identity labels with their ``le``
    # label; every other sample takes the plain label set.
    le_prefix = f"{labels}," if labels else ""
    blocks = []
    for metric, original, kind, value in rows:
        blocks.append((metric, [
            f"# HELP {metric} "
            f"{_escape_help(f'bcg_tpu registry {kind} {original!r}')}",
            f"# TYPE {metric} {kind}",
            f"{metric}{wrap} {_format_value(value)}",
        ]))
    for name, hist in typed.get("histograms", {}).items():
        metric = prometheus_name(name)
        lines = [
            f"# HELP {metric} "
            f"{_escape_help(f'bcg_tpu registry histogram {name!r}')}",
            f"# TYPE {metric} histogram",
        ]
        for bound, cum in hist.get("buckets", []):
            lines.append(
                f'{metric}_bucket{{{le_prefix}le="{_format_value(bound)}"}} '
                f"{_format_value(cum)}"
            )
        lines.append(f'{metric}_bucket{{{le_prefix}le="+Inf"}} '
                     f"{_format_value(hist.get('count', 0))}")
        lines.append(f"{metric}_sum{wrap} "
                     f"{_format_value(hist.get('sum', 0.0))}")
        lines.append(f"{metric}_count{wrap} "
                     f"{_format_value(hist.get('count', 0))}")
        blocks.append((metric, lines))
    provider = _extra_blocks_provider
    if provider is not None:
        blocks.extend(provider(labels))
    out = []
    for _, lines in sorted(blocks, key=lambda b: b[0]):
        out.extend(lines)
    return "\n".join(out) + ("\n" if out else "")


# ------------------------------------------------------------ JSONL events

# Version of the JSONL record schemas BOTH sinks emit (serve lifecycle
# events and game events).  Bump on any breaking field change — offline
# aggregators (scripts/consensus_report.py) key merging decisions on it.
EVENT_SCHEMA_VERSION = 1


def run_manifest(**extra: Any) -> Dict[str, Any]:
    """The run-manifest header every JSONL sink writes as its FIRST
    record: run id, schema version, fleet identity, and the registered
    env-flag overrides in effect — so merging event files across a
    sweep (or across the ranks of one multi-process run) is mechanical
    (group by manifest run id + config, no out-of-band bookkeeping).
    ``extra`` fields (preset, game geometry) ride along verbatim.

    The run id comes from the fleet identity: ``BCG_TPU_RUN_ID`` when a
    launcher set one (all ranks — and both sinks of one process —
    share it), else a stable per-process 12-hex id.  ``metrics_port``
    surfaces the rank's ACTUAL ``/metrics`` port (the configured base
    offset by process_index) so a scraper can find every rank of a
    local cluster from the event files alone."""
    ident = obs_fleet.identity()
    manifest = {
        "schema_version": EVENT_SCHEMA_VERSION,
        "run_id": ident["run_id"],
        "pid": os.getpid(),
        "host": ident["host"],
        "process_index": ident["process_index"],
        "process_count": ident["process_count"],
        "metrics_port": current_http_port(),
        "flags": envflags.overrides(),
    }
    manifest.update(extra)
    return manifest


class EventSink:
    """Append-only JSONL event stream (one JSON object per line),
    written by a dedicated drainer thread.

    ``emit()`` only appends to a bounded in-memory queue — the scheduler
    calls it from its dispatch loop and (on failure paths) while holding
    its condition lock, so a stalled disk must never turn the telemetry
    sink into a serving-latency cliff.  The drainer opens the file
    lazily, writes in batches and flushes per batch (the file stays
    tail-able live); a full queue drops the OLDEST records and counts
    the loss in ``serve.events_dropped``; ``close()`` drains what is
    queued before returning (an atexit hook closes the process sink so
    a normal exit loses nothing)."""

    def __init__(self, path: str, max_queue: int = 65536,
                 drop_counter: str = "serve.events_dropped",
                 manifest: Optional[Dict[str, Any]] = None):
        self.path = path
        self._drop_counter = drop_counter
        self._cond = threading.Condition()
        self._queue: "deque" = deque(maxlen=max_queue)
        self._closed = False
        self._write_failed = False
        self._thread = threading.Thread(
            target=self._drain, name="bcg-event-sink", daemon=True
        )
        self._thread.start()
        if manifest is not None:
            # First record in the file: the run manifest (schema
            # version, run id, flag overrides) — sweep-level merging
            # keys on it.
            self.emit("manifest", **manifest)

    def emit(self, event: str, **fields: Any) -> None:
        record = {"ts": time.time(), "event": event}
        record.update(fields)
        with self._cond:
            if self._closed:
                return
            if self._write_failed:
                # Dead disk: the drainer can never land this record —
                # count the loss HERE and skip the queue entirely
                # (records already queued when the disk died are
                # counted by the drainer as it discards them, so every
                # lost record is accounted exactly once).
                obs_counters.inc(self._drop_counter)
                return
            if len(self._queue) == self._queue.maxlen:
                # deque(maxlen) evicts the oldest on append — count it.
                obs_counters.inc(self._drop_counter)
            self._queue.append(record)
            self._cond.notify()

    def _drain(self) -> None:
        fh = None
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait()
                batch = list(self._queue)
                self._queue.clear()
                closed = self._closed
                self._cond.notify_all()  # close() waits for empty queue
            if batch and self._write_failed:
                # Queue residue from before the disk died (or from the
                # emit-side race window): discarded, and counted — a
                # dead disk must show up as events_dropped accounting,
                # not as a silently thinner event file.
                obs_counters.inc(self._drop_counter, len(batch))
            elif batch:
                written = 0
                try:
                    if fh is None:
                        fh = open(self.path, "a", encoding="utf-8")
                    # Chaos seam (BCG_TPU_CHAOS `diskfail@sink.write`):
                    # the injected OSError takes exactly the dead-disk
                    # path below — warn once, drop-and-count after.
                    resilience.inject("sink.write")
                    for record in batch:
                        fh.write(json.dumps(record, default=str) + "\n")
                        written += 1
                    fh.flush()
                except OSError as exc:
                    import sys

                    # One warning, then drop-and-count: retrying a dead
                    # disk per batch would just spin this thread.
                    print(
                        f"obs.export: event sink write failed "
                        f"({self.path}): {exc} — further events dropped "
                        f"(counted in {self._drop_counter})",
                        file=sys.stderr,
                    )
                    self._write_failed = True
                    # Exactly-once accounting on a MID-BATCH failure:
                    # records that never reached fh.write are lost —
                    # count them now.  Records already buffered are
                    # decided by the close below: flushed to disk =
                    # written (not dropped), close also failing = lost
                    # (counted) — never both on disk AND in the drop
                    # counter.
                    obs_counters.inc(self._drop_counter,
                                     len(batch) - written)
                    if fh is not None:
                        try:
                            fh.close()
                        except OSError:
                            obs_counters.inc(self._drop_counter, written)
                        fh = None
            if closed:
                break
        if fh is not None:
            fh.close()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout=10.0)


_sink_lock = threading.Lock()
_sink: Optional[EventSink] = None
_sink_configured = False


def _ensure_sink() -> Optional[EventSink]:
    global _sink, _sink_configured
    if _sink_configured:
        return _sink
    with _sink_lock:
        if not _sink_configured:
            path = envflags.get_str("BCG_TPU_SERVE_EVENTS")
            if path:
                _sink = EventSink(path, manifest=run_manifest(kind="serve"))
                # Drain the queue on normal interpreter exit — the
                # writer is a daemon thread and would otherwise die
                # with the tail of the run still in memory.
                atexit.register(reset_sink)
            _sink_configured = True
    return _sink


def emit_event(event: str, **fields: Any) -> None:
    """Queue one lifecycle event for the configured sink (no-op when
    ``BCG_TPU_SERVE_EVENTS`` is unset).  Non-blocking by construction —
    the scheduler calls this from its dispatch loop and, on failure
    paths, under its condition lock; disk latency lives entirely on the
    sink's drainer thread."""
    sink = _ensure_sink()
    if sink is not None:
        sink.emit(event, **fields)


def reset_sink() -> None:
    """Drop the cached sink + its read-once flag — TEST-ONLY."""
    global _sink, _sink_configured
    with _sink_lock:
        if _sink is not None:
            _sink.close()
        _sink = None
        _sink_configured = False


# ------------------------------------------------------------ HTTP server
_server_lock = threading.Lock()
_server = None
_server_port: Optional[int] = None


def start_http_server(port: int) -> Tuple[Any, int]:
    """Start the metrics endpoint on ``port`` (0 = ephemeral) and return
    ``(server, bound_port)``.  The server thread is a daemon; call
    ``server.shutdown()`` to stop it (tests do; production lets process
    exit reap it)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (stdlib casing)
            path = self.path.split("?")[0]
            if path in ("/healthz", "/readyz"):
                # Lazy import: alerts imports this module for its
                # EventSink, so the reverse edge stays request-time.
                from bcg_tpu.obs import alerts as obs_alerts

                ok, detail = (obs_alerts.health() if path == "/healthz"
                              else obs_alerts.readiness())
                body = (json.dumps(detail, sort_keys=True) + "\n"
                        ).encode("utf-8")
                self.send_response(200 if ok else 503)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if path not in ("/metrics", "/"):
                self.send_response(404)
                self.end_headers()
                return
            body = render_prometheus().encode("utf-8")
            self.send_response(200)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
            )
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # silence per-scrape stderr noise
            pass

    server = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
    thread = threading.Thread(
        target=server.serve_forever, name="bcg-metrics-http", daemon=True
    )
    thread.start()
    return server, server.server_address[1]


def current_http_port() -> Optional[int]:
    """The bound ``/metrics`` port, or None while the endpoint is off —
    the run-manifest field (surfaced so every rank of a local cluster
    is discoverable from its event files)."""
    return _server_port


def maybe_start_http_server() -> Optional[int]:
    """Start the endpoint once per process when ``BCG_TPU_METRICS_PORT``
    is set (> 0); returns the bound port, or None when disabled.  Called
    from engine/scheduler boot — cheap no-op on every later call.

    The configured port is a BASE: each rank binds base +
    process_index, so every rank of a local multi-process cluster is
    scrapeable instead of rank 0 binding and the rest warn-and-skipping
    on the collision (single-process: process_index 0, port unchanged).
    """
    global _server, _server_port
    if _server is not None:
        return _server_port
    port = envflags.get_int("BCG_TPU_METRICS_PORT")
    if port <= 0:
        return None
    port += obs_fleet.process_index()
    with _server_lock:
        if _server is None:
            try:
                _server, _server_port = start_http_server(port)
            except OSError as exc:
                import sys

                print(
                    f"obs.export: metrics endpoint failed to bind port "
                    f"{port}: {exc} — telemetry HTTP disabled",
                    file=sys.stderr,
                )
                return None
    return _server_port


def stop_http_server() -> None:
    """Shut the process endpoint down (TEST-ONLY; production relies on
    daemon-thread teardown at exit)."""
    global _server, _server_port
    with _server_lock:
        if _server is not None:
            _server.shutdown()
            _server.server_close()
            _server = None
            _server_port = None
