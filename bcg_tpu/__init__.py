"""bcg_tpu — TPU-native Byzantine Consensus Game framework.

A ground-up re-design of ``leorugli/byzantine-consensus-llm-agents`` for TPU
hardware.  The reference drives every agent decision through a CUDA-backed
vLLM engine; this framework replaces that engine with a JAX/XLA/Pallas
inference stack (sharded weights over an ICI mesh, jitted autoregressive
decode, schema-guided JSON decoding as an in-graph token-DFA mask) while
keeping behavioural parity with the reference's game semantics, agent
prompts, metrics, and CLI.

Layer map (mirrors reference layers, reference file in parens):

* ``bcg_tpu.config``    — typed, immutable config system   (config.py)
* ``bcg_tpu.comm``      — protocol ABCs, A2A-sim, topology (communication_protocol.py,
                          a2a_sim.py, agent_network.py, protocol_factory.py)
* ``bcg_tpu.game``      — consensus state machine + stats  (byzantine_consensus.py)
* ``bcg_tpu.agents``    — honest/Byzantine LLM agents      (bcg_agents.py)
* ``bcg_tpu.engine``    — inference engines: JAX + fake    (vllm_agent.py)
* ``bcg_tpu.models``    — decoder-only transformer family  (vLLM-internal in reference)
* ``bcg_tpu.ops``       — Pallas/TPU kernels               (CUDA kernels in reference)
* ``bcg_tpu.guided``    — JSON-schema guided decoding DFA  (vLLM GuidedDecodingParams)
* ``bcg_tpu.parallel``  — mesh / sharding / collectives    (NCCL via torch.distributed)
* ``bcg_tpu.runtime``   — orchestrator, metrics, CLI       (main.py)
"""

__version__ = "0.1.0"
