"""CLI entry point (reference ``main.py:998-1070``).

Same flags as the reference plus TPU-era additions (--backend, --model,
--seed, --topology, --results-dir, --checkpoint-every-round, --resume).

    python -m bcg_tpu.cli --honest 8 --byzantine 2 --rounds 50
    python -m bcg_tpu.cli --honest 4 --byzantine 0 --backend fake --seed 0
"""

from __future__ import annotations

import argparse
import dataclasses
import sys
from typing import Optional

from bcg_tpu.config import BCGConfig, resolve_model_name


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="Byzantine Consensus Game Simulation (TPU-native)")
    p.add_argument("--honest", type=int, default=None, help="Number of honest agents")
    p.add_argument("--byzantine", type=int, default=None, help="Number of Byzantine agents (can be 0)")
    p.add_argument("--rounds", type=int, default=None, help="Max number of rounds")
    p.add_argument("--threshold", type=float, default=None, help="Reported majority agreement percentage (default: 66)")
    p.add_argument("--value-range", type=str, default=None, help="Value range as 'min-max' (default: 0-50)")
    p.add_argument(
        "--byzantine-awareness",
        type=str,
        default="may_exist",
        choices=["may_exist", "none_exist"],
        help="Whether honest agents are told Byzantine agents may exist",
    )
    p.add_argument("--verbose", action="store_true", help="Print detailed output to terminal")
    # TPU-era additions
    p.add_argument("--backend", type=str, default=None, choices=["jax", "fake"], help="Inference backend")
    p.add_argument("--model", type=str, default=None, help="Model preset key or full path")
    p.add_argument("--seed", type=int, default=None, help="Game RNG seed (reproducible runs)")
    p.add_argument("--topology", type=str, default=None, choices=["fully_connected", "ring", "grid"], help="Network topology")
    p.add_argument("--spmd-exchange", action="store_true",
                   help="Exchange values via XLA collectives (one all_gather) instead of the host message loop")
    p.add_argument("--serve", action="store_true",
                   help="Route LLM calls through the continuous-batching "
                        "serving scheduler (bcg_tpu/serve; also enabled by "
                        "BCG_TPU_SERVE=1) — prints scheduler stats on exit "
                        "with --verbose")
    p.add_argument("--results-dir", type=str, default=None, help="Results directory")
    p.add_argument("--no-save", action="store_true", help="Disable result files")
    p.add_argument("--plots", action="store_true", help="Save per-run plots (value trajectories, agreement)")
    p.add_argument("--profile-dir", type=str, default=None,
                   help="Write a jax.profiler trace of the run to this directory")
    p.add_argument("--checkpoint-every-round", action="store_true", help="Write a resumable checkpoint after each round")
    p.add_argument("--resume", type=str, default=None, help="Resume from checkpoint file")
    p.add_argument("--tensor-parallel", type=int, default=None, help="TP mesh axis size")
    p.add_argument("--sequence-parallel", type=int, default=None,
                   help="SP mesh axis size (ring-attention long-context prefill)")
    p.add_argument("--data-parallel", type=int, default=None,
                   help="DP mesh axis size (agent parallelism: game batches "
                        "shard one-row-per-device-slice; BASELINE config 4's "
                        "one-agent-per-chip layout when it equals the agent "
                        "count)")
    p.add_argument("--quantization", type=str, default=None, choices=["int8", "int4"],
                   help="Weight quantization: int8 = dynamic W8A8 (halves decode "
                        "weight traffic); int4 = grouped W4A16 (capacity: fits "
                        "the 14B preset on one 16 GB chip)")
    p.add_argument("--kv-cache-dtype", type=str, default=None, choices=["bfloat16", "int8"],
                   help="KV cache storage dtype (int8 halves decode cache traffic)")
    p.add_argument("--no-prefix-caching", action="store_true",
                   help="Disable system-prompt KV prefix caching")
    p.add_argument("--fine-suffix-buckets", action="store_true",
                   help="Finer suffix-length buckets (1536/3072 rungs): less pad "
                        "traffic in the decode window, more compile signatures")
    p.add_argument("--scan-layers", action="store_true",
                   help="Run the layer stack as one lax.scan (O(1)-in-depth program; "
                        "needed for 8B-class compiles)")
    p.add_argument("--fast-forward", action="store_true",
                   help="Forced-chain fast-forward decoding (skeleton tokens ride the sampled token's weight pass)")
    p.add_argument("--spec-decode", action="store_true",
                   help="Prompt-lookup speculative decoding: n-gram drafts from the "
                        "row's own history verified K+1 tokens per weight pass "
                        "(token-identical at temperature 0; supersedes --fast-forward)")
    p.add_argument("--compact-json", action="store_true",
                   help="Compact-JSON generation grammar (no inter-token whitespace)")
    p.add_argument("--shared-core-votes", action="store_true",
                   help="Serve the vote phase's shared proposals/history block from a "
                        "cached KV prefix (restructures vote prompts; opt-in because "
                        "the text diverges from the reference's format)")
    p.add_argument("--fake-policy", type=str, default=None,
                   help="Fake-backend scripted policy: consensus|schema_min|"
                        "stubborn|median|disrupt|oscillate|mimic|silent, or "
                        "mixed:<honest>:<byzantine> for a role-aware mix")
    p.add_argument("--fault-rate", type=float, default=None,
                   help="Corrupt this fraction of LLM responses (resilience experiments)")
    p.add_argument("--fault-seed", type=int, default=None,
                   help="Seed for fault injection")
    p.add_argument("--protocol", type=str, default=None,
                   choices=["a2a_sim", "lossy_sim"],
                   help="Communication protocol (lossy_sim adds seeded message drops/delays)")
    p.add_argument("--drop-prob", type=float, default=None,
                   help="lossy_sim: per-message drop probability")
    p.add_argument("--delay-prob", type=float, default=None,
                   help="lossy_sim: probability a message arrives 1..max-delay rounds late")
    p.add_argument("--max-delay", type=int, default=None,
                   help="lossy_sim: maximum delivery delay in rounds")
    return p


def config_from_args(args) -> BCGConfig:
    base = BCGConfig()
    game = base.game
    if args.value_range:
        try:
            lo, hi = map(int, args.value_range.split("-"))
        except ValueError:
            raise SystemExit(
                f"Error: Invalid value range format '{args.value_range}'. Use 'min-max' (e.g., 0-50)"
            )
        value_range = (lo, hi)
    else:
        value_range = game.value_range

    game = dataclasses.replace(
        game,
        num_honest=args.honest if args.honest is not None else game.num_honest,
        num_byzantine=args.byzantine if args.byzantine is not None else game.num_byzantine,
        max_rounds=args.rounds if args.rounds is not None else game.max_rounds,
        consensus_threshold=args.threshold if args.threshold is not None else game.consensus_threshold,
        value_range=value_range,
        byzantine_awareness=args.byzantine_awareness,
        seed=args.seed,
    )
    engine = base.engine
    if args.backend:
        engine = dataclasses.replace(engine, backend=args.backend)
    if args.model:
        engine = dataclasses.replace(engine, model_name=resolve_model_name(args.model))
    if args.tensor_parallel:
        engine = dataclasses.replace(engine, tensor_parallel_size=args.tensor_parallel)
    if args.sequence_parallel:
        engine = dataclasses.replace(
            engine, sequence_parallel_size=args.sequence_parallel
        )
    if args.data_parallel:
        engine = dataclasses.replace(
            engine, data_parallel_size=args.data_parallel
        )
    if args.quantization:
        engine = dataclasses.replace(engine, quantization=args.quantization)
    if args.kv_cache_dtype:
        engine = dataclasses.replace(engine, kv_cache_dtype=args.kv_cache_dtype)
    if args.no_prefix_caching:
        engine = dataclasses.replace(engine, prefix_caching=False)
    if args.scan_layers:
        engine = dataclasses.replace(engine, scan_layers=True)
    if args.fine_suffix_buckets:
        engine = dataclasses.replace(engine, fine_suffix_buckets=True)
    if args.fast_forward:
        engine = dataclasses.replace(engine, decode_fast_forward=True)
    if args.spec_decode:
        engine = dataclasses.replace(engine, spec_decode=True)
    if args.compact_json:
        engine = dataclasses.replace(engine, guided_compact_json=True)
    if args.fault_rate is not None:
        engine = dataclasses.replace(engine, fault_rate=args.fault_rate)
    if args.fake_policy is not None:
        engine = dataclasses.replace(engine, fake_policy=args.fake_policy)
    if args.fault_seed is not None:
        engine = dataclasses.replace(engine, fault_seed=args.fault_seed)
    network = base.network
    if args.topology:
        network = dataclasses.replace(network, topology_type=args.topology)
    if args.spmd_exchange:
        network = dataclasses.replace(network, spmd_exchange=True)
    communication = base.communication
    if args.protocol:
        communication = dataclasses.replace(communication, protocol_type=args.protocol)
    channel_knobs = (args.drop_prob, args.delay_prob, args.max_delay)
    if any(k is not None for k in channel_knobs) and \
            communication.protocol_type != "lossy_sim":
        # The reliable channel ignores these — running a "30%-loss"
        # experiment over a perfect channel must fail loudly, not
        # silently produce wrong science.
        raise SystemExit(
            "Error: --drop-prob/--delay-prob/--max-delay require "
            "--protocol lossy_sim"
        )
    if args.drop_prob is not None:
        communication = dataclasses.replace(communication, drop_prob=args.drop_prob)
    if args.delay_prob is not None:
        communication = dataclasses.replace(communication, delay_prob=args.delay_prob)
    if args.max_delay is not None:
        communication = dataclasses.replace(communication, max_delay_rounds=args.max_delay)
    agent = base.agent
    if args.shared_core_votes:
        agent = dataclasses.replace(agent, shared_core_votes=True)
    metrics = base.metrics
    if args.results_dir:
        metrics = dataclasses.replace(metrics, results_dir=args.results_dir)
    if args.no_save:
        metrics = dataclasses.replace(metrics, save_results=False)
    if args.checkpoint_every_round:
        metrics = dataclasses.replace(metrics, checkpoint_every_round=True)
    if args.plots:
        metrics = dataclasses.replace(metrics, generate_plots=True)

    return BCGConfig(
        game=game,
        network=network,
        communication=communication,
        engine=engine,
        agent=agent,
        metrics=metrics,
        verbose=args.verbose,
    )


def main(argv: Optional[list] = None) -> int:
    args = build_parser().parse_args(argv)
    config = config_from_args(args)

    print("=" * 60)
    print("Configuration:")
    print(f"  Honest agents: {config.game.num_honest}")
    print(f"  Byzantine agents: {config.game.num_byzantine}")
    print(f"  Value range: {config.game.value_range[0]}-{config.game.value_range[1]}")
    print(f"  Max rounds: {config.game.max_rounds}")
    print(f"  Consensus threshold: {config.game.consensus_threshold}%")
    print(f"  Byzantine awareness: {config.game.byzantine_awareness}")
    print(f"  Backend: {config.engine.backend} ({config.engine.model_name})")
    print("=" * 60)

    try:
        if args.resume:
            from bcg_tpu.runtime.checkpoint import resume_simulation

            sim = resume_simulation(args.resume, config=config)
        else:
            from bcg_tpu.runtime.orchestrator import BCGSimulation

            sim = BCGSimulation(config=config)
    except (ValueError, FileNotFoundError) as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    serving = None
    from bcg_tpu.runtime import envflags

    if args.serve or envflags.get_bool("BCG_TPU_SERVE"):
        from bcg_tpu.serve import ServingEngine

        from bcg_tpu.engine.interface import create_engine

        # Front the engine with the continuous-batching scheduler; it
        # owns the inner engine so one shutdown() releases both.  The
        # factory lets the supervisor reboot a hung engine from the
        # same config (BCG_TPU_SERVE_WATCHDOG_S).
        serving = ServingEngine(
            sim.engine, owns_inner=True,
            engine_factory=lambda: create_engine(config.engine),
        )
        sim.set_engine(serving)
    try:
        from bcg_tpu.runtime.profiler import jax_trace

        with jax_trace(args.profile_dir):
            sim.run()
        if serving is not None and config.verbose:
            import json as _json

            print("[Serving Scheduler]")
            print(_json.dumps(serving.stats(), indent=2))
    finally:
        sim.engine.shutdown()
        sim.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
