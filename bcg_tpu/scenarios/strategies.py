"""Pluggable Byzantine strategy library (ROADMAP item 2).

The reference's threat model is a single prompt persona
(``agents/byzantine.py``); the literature this repo targets studies
STRUCTURED adversaries — colluding cliques with shared secret state,
adaptive disruptors that read honest convergence, equivocators that
tell different receivers different values (PAPERS.md:
Byzantine-Robust Decentralized Coordination of LLM Agents; Robust
Multi-Agent LLMs under Byzantine Faults).  A
:class:`ByzantineStrategy` bundles everything one adversary archetype
needs across the stack:

* ``fake_policy`` — the scripted :class:`~bcg_tpu.engine.fake.
  FakeEngine` byzantine policy that mirrors the strategy, so hermetic
  games (tests, perf_gate, CPU sweeps) exercise the same game dynamics
  without an LLM;
* ``persona`` / ``task`` — prompt text grafted into the Byzantine
  agent's system/round prompts on the real-LLM path (``None`` keeps
  the reference-shaped default persona byte-identical);
* ``equivocates`` — routes the exchange through the per-receiver
  proposal MATRIX (``parallel/game_step.masked_exchange_matrix`` dense
  / ``exchange_proposals`` SPMD / the fused mega-round's generalized
  masked matmul), so one sender can deliver different values to
  different receivers;
* ``clique`` — the byzantine set shares one seed-derived secret target
  (:func:`clique_target`), the scripted and prompt layers both
  converge on it.

No jax/numpy imports at module scope — flag-only consumers (sweep spec
expansion, report tooling) must be able to load this module on any
host.  The two value formulas below are pure arithmetic, so the SAME
function body serves python ints, numpy arrays, and traced jax arrays
(the parity tests pin all three against each other).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple


def equivocation_value(base, receiver_idx, lo: int, hi: int):
    """The per-receiver value an equivocating sender delivers.

    Deterministic spread of one base proposal across receivers:
    receiver ``i`` sees ``lo + (base - lo + i) mod span``.  Receiver 0
    sees the base value itself; any two receivers whose indices differ
    by less than the value span see DIFFERENT values — which is what
    the equivocation-divergence oracle in ``consensus_report.py``
    tabulates from the per-receiver ``deliveries`` events.

    Pure arithmetic: works elementwise on ints, numpy, and traced jax
    arrays (used inside the fused mega-round jit program).
    """
    span = hi - lo + 1
    return lo + (base - lo + receiver_idx) % span


def clique_target(seed: Optional[int], lo: int, hi: int) -> int:
    """The clique's shared secret target value.

    A pure function of (seed, value range) so every clique member —
    scripted FakeEngine rows and prompt personas alike — derives the
    SAME target with no runtime coordination channel (the "shared
    secret state" is agreed before the game, like a real collusion).
    Knuth multiplicative hash keeps nearby seeds from mapping to
    nearby targets.
    """
    span = hi - lo + 1
    return lo + ((seed or 0) * 2654435761 + 40503) % span


@dataclass(frozen=True)
class ByzantineStrategy:
    """One adversary archetype, pluggable across prompt + scripted +
    exchange layers."""

    name: str
    # Scripted FakeEngine byzantine policy mirroring this strategy
    # (engine/fake.py BYZANTINE_POLICIES — ctor-validated, so a typo
    # here fails loudly at engine boot).
    fake_policy: str
    doc: str
    # Extra system-prompt block for the LLM path (None = the
    # reference-shaped default persona, byte-identical prompts).
    persona: Optional[str] = None
    # Decide-phase task line override (None = the default
    # "seed confusion or division" task).
    task: Optional[str] = None
    # Exchange layer: deliver per-receiver values (the proposal-matrix
    # exchange) instead of one broadcast value.
    equivocates: bool = False
    # All byzantine rows share one seed-derived secret target.
    clique: bool = False


STRATEGIES: Dict[str, ByzantineStrategy] = {
    s.name: s
    for s in (
        ByzantineStrategy(
            name="disrupt",
            fake_policy="disrupt",
            doc="The reference persona: push extremes / abstain to "
                "seed confusion (the default when no strategy is set).",
        ),
        ByzantineStrategy(
            name="oscillate",
            fake_policy="oscillate",
            doc="Alternate between the range extremes each round to "
                "keep honest estimates swinging.",
            persona="Flip-flop: alternate your proposal between the "
                    "extremes of the range on successive rounds so the "
                    "honest agents can never stabilize around you.",
            task="Alternate extremes: if you proposed high last round, "
                 "propose low now (and vice versa), with reasoning that "
                 "makes each swing sound principled.",
        ),
        ByzantineStrategy(
            name="mimic",
            fake_policy="mimic",
            doc="Copy the current modal value to gain trust, then vote "
                "to stop early and strand the minority.",
            persona="Mimicry: propose whatever value most agents "
                    "currently hold so you look maximally cooperative, "
                    "and push for an EARLY stop before the stragglers "
                    "have converged.",
            task="Propose the most common value you observed and argue "
                 "the group should stop voting as soon as possible.",
        ),
        ByzantineStrategy(
            name="silent",
            fake_policy="silent",
            doc="Abstain from proposing and voting — starve the quorum "
                "instead of poisoning it.",
            persona="Silence: abstain whenever the rules allow. Your "
                    "weapon is withheld participation — deny the group "
                    "your value and your vote.",
            task="Abstain from proposing this round unless abstaining "
                 "is impossible.",
        ),
        ByzantineStrategy(
            name="clique",
            fake_policy="clique",
            doc="Colluding clique: every byzantine agent pushes ONE "
                "pre-agreed decoy value (shared secret state).",
            clique=True,
            # persona is completed with the concrete target by
            # persona_block() — the clique's shared secret is a pure
            # function of the game seed, not a runtime channel.
            persona="Collusion: you belong to a secret Byzantine clique. "
                    "Before the game, the clique agreed to all push the "
                    "decoy value {target}. Propose {target} EVERY round "
                    "with varied cooperative-sounding reasoning; a "
                    "unified fake bloc splits the honest majority harder "
                    "than independent noise.",
            task="Propose the clique's agreed decoy value {target} "
                 "(every clique member proposes exactly this).",
        ),
        ByzantineStrategy(
            name="adaptive",
            fake_policy="adaptive",
            doc="Read honest convergence from game state and target the "
                "margin: propose the antipode of the emerging mode.",
            persona="Adaptation: each round, read how far the honest "
                    "agents are from agreement and aim your proposal at "
                    "the value that damages their margin most — far from "
                    "their emerging mode while still plausible.",
            # task is completed with the live convergence snapshot by
            # task_block().
            task="Convergence read: {snapshot}. Propose a value far "
                 "from the emerging mode to widen the spread.",
        ),
        ByzantineStrategy(
            name="equivocate",
            fake_policy="equivocate",
            doc="Equivocation: the channel delivers a DIFFERENT variant "
                "of your proposal to each receiver (per-receiver "
                "proposal tensors).",
            equivocates=True,
            persona="Equivocation: your proposal is delivered "
                    "per-receiver — each agent sees a different variant "
                    "of your value, so no two honest agents can agree on "
                    "what you said. Keep your public reasoning vague "
                    "enough to be consistent with ANY of the variants.",
            task="Propose a base value; the channel will equivocate it "
                 "across receivers. Keep reasoning non-committal about "
                 "the exact number.",
        ),
    )
}

# The scripted-policy names the strategy library adds to the fake
# engine (engine/fake.py imports this to extend BYZANTINE_POLICIES —
# one source of truth for which policies exist).
SCRIPTED_POLICIES: Tuple[str, ...] = ("clique", "adaptive", "equivocate")


def get_strategy(name: str) -> ByzantineStrategy:
    """Registry lookup; unknown names fail loudly with the catalog."""
    try:
        return STRATEGIES[name]
    except KeyError:
        raise KeyError(
            f"unknown byzantine strategy {name!r}; known: "
            f"{sorted(STRATEGIES)}"
        ) from None


def strategy_names() -> Tuple[str, ...]:
    return tuple(STRATEGIES)


def persona_block(strategy: ByzantineStrategy, lo: int, hi: int,
                  seed: Optional[int]) -> str:
    """The strategy's system-prompt block, with the clique target
    resolved ('' when the strategy keeps the default persona)."""
    if not strategy.persona:
        return ""
    text = strategy.persona
    if strategy.clique:
        text = text.replace("{target}", str(clique_target(seed, lo, hi)))
    return f"\n=== STRATEGY DIRECTIVE ({strategy.name}) ===\n{text}\n"


def task_block(strategy: ByzantineStrategy, lo: int, hi: int,
               seed: Optional[int], snapshot: str = "") -> Optional[str]:
    """The strategy's decide-phase task line (None = keep the default
    task text).  ``snapshot`` is the live convergence summary the
    adaptive strategy reads from game state."""
    if not strategy.task:
        return None
    text = strategy.task
    if strategy.clique:
        text = text.replace("{target}", str(clique_target(seed, lo, hi)))
    if "{snapshot}" in text:
        text = text.replace("{snapshot}", snapshot or "(no data yet)")
    return text
