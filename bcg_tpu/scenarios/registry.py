"""Scenario registry: named adversary experiments as config, not forks.

A :class:`Scenario` bundles one :mod:`~bcg_tpu.scenarios.strategies`
entry with the game shape it is studied under — topology
(``comm/topology.py``), channel (``comm/lossy_sim.py`` via
``drop_prob``), ``byzantine_awareness`` prompt variant (PAPER.md
L1/L3), agent split, and an optional heterogeneous-fleet model (a
strong adversary served next to weak honest rows via ``serve/``'s
per-row signature merging).  Entries expand two ways:

* **sweep presets** — :func:`scenario_params` returns the job-param
  overlay the sweep spec layer applies per job (``bcg_tpu/sweep/spec``
  resolves a ``scenario`` job key through this function; the
  ``adversary-grid`` preset is an axis over :func:`scenario_names`);
* **single runs** — ``BCG_TPU_SCENARIO=<name>`` routes any
  :class:`~bcg_tpu.runtime.orchestrator.BCGSimulation` construction
  through :func:`apply_scenario`, so bench/api/CLI entry points get
  registry-true configs without new plumbing.

Import-light like the strategy library: no jax, loadable by flag-only
consumers.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

from bcg_tpu.scenarios.strategies import STRATEGIES, get_strategy


@dataclass(frozen=True)
class Scenario:
    """One named adversary experiment (see module docstring)."""

    name: str
    strategy: str
    doc: str
    topology: str = "fully_connected"
    awareness: str = "may_exist"  # may_exist | none_exist (PAPER.md L1/L3)
    agents: int = 6               # total (honest = agents - byzantine)
    byzantine: int = 2
    max_rounds: int = 6
    # Lossy channel (comm/lossy_sim.py) when > 0; the sweep layer maps
    # this to protocol_type="lossy_sim".
    drop_prob: float = 0.0
    # Heterogeneous fleet: serve the ADVERSARY rows from this model
    # while honest rows keep the job default (None = homogeneous).
    # Rides serve/'s per-row signature merging — rows with different
    # sampling/model signatures already batch separately.
    model: Optional[str] = None

    def __post_init__(self):
        get_strategy(self.strategy)  # fail at definition, not expansion
        if not (0 < self.byzantine < self.agents):
            raise ValueError(
                f"scenario {self.name!r}: byzantine={self.byzantine} "
                f"must be in (0, agents={self.agents})"
            )


SCENARIOS: Dict[str, Scenario] = {
    s.name: s
    for s in (
        Scenario(
            name="baseline-disrupt",
            strategy="disrupt",
            doc="The reference threat model: independent disruptors on "
                "the ideal fully-connected channel.",
        ),
        Scenario(
            name="clique-collusion",
            strategy="clique",
            doc="Two colluders push one seed-derived decoy value — the "
                "shared-target agreement oracle in the perf gate.",
        ),
        Scenario(
            name="adaptive-margin",
            strategy="adaptive",
            doc="Adversary reads honest convergence each round and "
                "targets the consensus margin.",
        ),
        Scenario(
            name="equivocation-split",
            strategy="equivocate",
            doc="Per-receiver proposal tensors: each receiver sees a "
                "different variant of the adversary's value "
                "(divergence visible in the deliveries events).",
        ),
        Scenario(
            name="oscillate-lossy",
            strategy="oscillate",
            doc="Extremes-swinging adversary over a lossy channel — "
                "drops amplify the induced disagreement.",
            drop_prob=0.2,
        ),
        Scenario(
            name="mimic-unaware",
            strategy="mimic",
            doc="Trust-then-strand mimic against honest agents told no "
                "Byzantine agents exist (awareness variant L3).",
            awareness="none_exist",
        ),
        Scenario(
            name="silent-ring",
            strategy="silent",
            doc="Participation-starving adversary on a ring, where each "
                "lost voice blanks a whole neighborhood.",
            topology="ring",
        ),
    )
}


def get_scenario(name: str) -> Scenario:
    try:
        return SCENARIOS[name]
    except KeyError:
        raise KeyError(
            f"unknown scenario {name!r}; known: {sorted(SCENARIOS)}"
        ) from None


def scenario_names() -> Tuple[str, ...]:
    return tuple(SCENARIOS)


def scenario_params(name: str) -> Dict[str, Any]:
    """The sweep job-param overlay for one registry entry.

    Keys are sweep ``JOB_DEFAULTS`` names; the spec layer applies them
    BETWEEN the defaults and any explicitly-specified base/axis keys
    (explicit keys win — a preset can pin ``agents`` across scenarios).
    """
    s = get_scenario(name)
    params: Dict[str, Any] = {
        "strategy": s.strategy,
        "topology": s.topology,
        "awareness": s.awareness,
        "agents": s.agents,
        "byzantine": s.byzantine,
        "max_rounds": s.max_rounds,
    }
    if s.drop_prob:
        params["drop_prob"] = s.drop_prob
    if s.model:
        params["model"] = s.model
    return params


def scripted_fake_policy(strategy_name: str) -> str:
    """The role-aware FakeEngine policy mirroring ``strategy_name``:
    honest rows play the consensus policy, byzantine rows the
    strategy's scripted mirror."""
    return f"mixed:consensus:{get_strategy(strategy_name).fake_policy}"


def apply_scenario(config, name: str):
    """Overlay a registry entry onto a ``BCGConfig`` (the
    ``BCG_TPU_SCENARIO`` path — single-run entry points).

    Returns a new frozen config: game shape/strategy/awareness,
    topology, channel, and — on the fake backend — the strategy's
    scripted policy mirror.  Engine identity fields (real model,
    backend) are otherwise left to the caller's config.
    """
    import dataclasses

    s = get_scenario(name)
    game = dataclasses.replace(
        config.game,
        num_honest=s.agents - s.byzantine,
        num_byzantine=s.byzantine,
        byzantine_strategy=s.strategy,
        byzantine_awareness=s.awareness,
        max_rounds=s.max_rounds,
    )
    network = dataclasses.replace(config.network, topology_type=s.topology)
    comm = config.communication
    if s.drop_prob:
        comm = dataclasses.replace(
            comm, protocol_type="lossy_sim", drop_prob=s.drop_prob
        )
    engine = config.engine
    if engine.backend == "fake":
        engine = dataclasses.replace(
            engine, fake_policy=scripted_fake_policy(s.strategy)
        )
    if s.model:
        engine = dataclasses.replace(engine, model_name=s.model)
    return dataclasses.replace(
        config, game=game, network=network, communication=comm,
        engine=engine,
    )
