"""Adversary library + scenario registry (ROADMAP item 2).

``strategies`` — pluggable Byzantine strategy objects (prompt persona
+ scripted FakeEngine mirror + exchange semantics); ``registry`` —
named scenario entries that expand into sweep presets and single-run
configs (``BCG_TPU_SCENARIO``).
"""

from bcg_tpu.scenarios.registry import (
    SCENARIOS,
    Scenario,
    apply_scenario,
    get_scenario,
    scenario_names,
    scenario_params,
    scripted_fake_policy,
)
from bcg_tpu.scenarios.strategies import (
    SCRIPTED_POLICIES,
    STRATEGIES,
    ByzantineStrategy,
    clique_target,
    equivocation_value,
    get_strategy,
    persona_block,
    strategy_names,
    task_block,
)

__all__ = [
    "SCENARIOS",
    "SCRIPTED_POLICIES",
    "STRATEGIES",
    "ByzantineStrategy",
    "Scenario",
    "apply_scenario",
    "clique_target",
    "equivocation_value",
    "get_scenario",
    "get_strategy",
    "persona_block",
    "scenario_names",
    "scenario_params",
    "scripted_fake_policy",
    "strategy_names",
    "task_block",
]
