"""Programmatic batch-experiment API (reference ``main.py:1073-1141``).

``run_simulation`` runs one game with file-saving disabled and returns
``{"metrics": stats}``.  Unlike the reference, which temporarily mutates
METRICS_CONFIG/VLLM_CONFIG globals with a finally-restore dance
(main.py:1094-1102), each call here builds its own immutable config.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from bcg_tpu.config import BCGConfig, EngineConfig, resolve_model_name
from bcg_tpu.engine.interface import InferenceEngine


def resolve_engine_config(
    model_name: Optional[str],
    backend: Optional[str],
    base: Optional[BCGConfig] = None,
) -> EngineConfig:
    """The single place name/backend overrides become an EngineConfig —
    shared by per-run construction here and the concurrent-sweep shared
    engine in :mod:`bcg_tpu.experiments`, so both always agree."""
    engine_cfg = (base or BCGConfig()).engine
    if model_name:
        engine_cfg = dataclasses.replace(
            engine_cfg, model_name=resolve_model_name(model_name)
        )
    if backend:
        engine_cfg = dataclasses.replace(engine_cfg, backend=backend)
    return engine_cfg


def run_simulation(
    n_agents: int = 8,
    max_rounds: int = 50,
    model_name: Optional[str] = None,
    byzantine_count: int = 0,
    byzantine_awareness: str = "may_exist",
    backend: Optional[str] = None,
    seed: Optional[int] = None,
    engine: Optional[InferenceEngine] = None,
    config: Optional[BCGConfig] = None,
) -> dict:
    """Run a single simulation for batch experiments; no files written."""
    from bcg_tpu.runtime.orchestrator import BCGSimulation

    base = config or BCGConfig()
    game = dataclasses.replace(
        base.game,
        num_honest=n_agents - byzantine_count,
        num_byzantine=byzantine_count,
        max_rounds=max_rounds,
        byzantine_awareness=byzantine_awareness,
        seed=seed if seed is not None else base.game.seed,
    )
    engine_cfg = resolve_engine_config(model_name, backend, base=base)
    metrics = dataclasses.replace(base.metrics, save_results=False, generate_plots=False)

    created = engine is None
    run_engine = engine
    if created:
        from bcg_tpu.runtime import envflags

        if envflags.get_bool("BCG_TPU_SERVE"):
            # Serving-stack path: the internally created engine is
            # fronted by the continuous-batching scheduler proxy
            # (bcg_tpu/serve) — same results, arrival-driven dispatch.
            from bcg_tpu.engine.interface import create_engine
            from bcg_tpu.serve import ServingEngine

            run_engine = ServingEngine(
                create_engine(engine_cfg), owns_inner=True,
                # Supervisor rebuild hook (BCG_TPU_SERVE_WATCHDOG_S):
                # the wrap site owns the config, so a hung engine can
                # be rebooted from it.
                engine_factory=lambda: create_engine(engine_cfg),
            )

    try:
        sim = BCGSimulation(
            config=dataclasses.replace(base, game=game, engine=engine_cfg, metrics=metrics),
            engine=run_engine,
        )
    except BaseException:
        if created and run_engine is not None:
            # The serving wrapper (and its booted inner engine) exists
            # before the sim does — a constructor failure must not leak
            # device memory or the scheduler thread.
            run_engine.shutdown()
        raise
    try:
        while not sim.game.game_over:
            sim.run_round()
        stats = sim.game.get_statistics()
        stats["byzantine_awareness"] = byzantine_awareness
        return {"metrics": stats}
    finally:
        if created:
            # We created the engine internally; release its device
            # memory (and, on the serving path, the scheduler thread).
            sim.engine.shutdown()
        sim.close()
