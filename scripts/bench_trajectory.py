#!/usr/bin/env python
"""Cross-run bench trajectory: outages vs regressions, per-metric
trends vs best-known-good.

``python scripts/bench_trajectory.py BENCH_r*.json [--threshold 0.7]``
``python scripts/bench_trajectory.py <dir>``  (globs BENCH_r*.json)

BENCH_r01-r05 is the cautionary tale this script exists for: three
accelerator-attach outages (r03-r05) recorded ``vs_baseline: 0.0``
and read as catastrophic regressions until a human noticed the
``error`` field.  This script makes the distinction mechanical:

* **outage** — the run measured NOTHING: no parsed payload (driver
  crash, rc != 0 with an empty ``parsed``), an ``error`` field, or a
  null ``vs_baseline`` (the post-PR-6 outage marker).  Outages are
  REPORTED and EXCLUDED from regression analysis — an outage is not a
  0%-of-baseline measurement.
* **measured** — a real number.  The newest measured run is compared
  against the best-known-good (the max over every EARLIER measured
  run) per metric; a drop below ``--threshold`` (default 0.7) of
  best-known-good is a REGRESSION: named per metric on stderr, exit
  status 2 (pipefail-composable, the perf_gate contract).

Accepted file shape: the driver record ``{n, cmd, rc, tail, parsed}``
with the bench payload in ``parsed``, or a bare bench JSON (the
``parsed`` payload itself).  Runs order by the driver round number
``n`` when present, else by filename.

Self-contained — no bcg_tpu import — so a results directory copied off
a TPU host can be analyzed anywhere.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

# Metrics trended when present: (label, extractor) over the parsed
# payload.  `value` (decisions/sec) is the primary regression metric;
# the others trend informationally (vs_baseline moves with the
# denominator model class, so it trends but never gates alone).
TREND_METRICS = (
    ("decisions_per_sec", lambda p: p.get("value")),
    ("vs_baseline", lambda p: p.get("vs_baseline")),
    ("rounds_per_sec", lambda p: (p.get("extra") or {}).get("rounds_per_sec")),
    ("prefill_mfu", lambda p: (p.get("extra") or {}).get("prefill_mfu")),
    ("decode_gbps", lambda p: (p.get("extra") or {}).get("decode_gbps")),
)
PRIMARY_METRIC = "decisions_per_sec"


class Run:
    """One bench record: identity, classification, metric values."""

    __slots__ = ("label", "order", "rc", "status", "note", "metrics")

    def __init__(self, label: str, order, rc, status: str, note: str,
                 metrics: Dict[str, float]):
        self.label = label
        self.order = order
        self.rc = rc
        self.status = status  # "measured" | "outage"
        self.note = note
        self.metrics = metrics


def classify(parsed: Optional[dict], rc) -> Tuple[str, str]:
    """(status, note) for one run's parsed payload.

    Outage detection is deliberately belt-and-braces: the checked-in
    r03-r05 files predate the null-``vs_baseline`` convention (they
    carry ``vs_baseline: 0.0`` WITH an error field), so an ``error``
    field alone is already an outage; a null ``vs_baseline`` is the
    modern marker; an empty payload is a driver crash."""
    if not parsed:
        return "outage", (
            f"no parsed payload (driver rc={rc}) — run crashed before "
            "reporting"
        )
    error = parsed.get("error")
    if error:
        return "outage", str(error)[:120]
    if parsed.get("vs_baseline") is None:
        return "outage", "null vs_baseline — run measured nothing"
    value = parsed.get("value")
    if not isinstance(value, (int, float)) or value <= 0:
        return "outage", f"non-positive value {value!r} without an error field"
    return "measured", ""


def load_run(path: str) -> Run:
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict) and "parsed" in data:
        parsed = data.get("parsed") or {}
        rc = data.get("rc")
        order = data.get("n")
    else:  # bare bench payload
        parsed = data if isinstance(data, dict) else {}
        rc = None
        order = None
    status, note = classify(parsed, rc)
    metrics: Dict[str, float] = {}
    if status == "measured":
        for name, extract in TREND_METRICS:
            value = extract(parsed)
            if isinstance(value, (int, float)):
                metrics[name] = float(value)
    label = os.path.splitext(os.path.basename(path))[0]
    return Run(label, order, rc, status, note, metrics)


def order_runs(runs: List[Run]) -> List[Run]:
    """Driver round number when every run has one, else filename."""
    if all(r.order is not None for r in runs):
        return sorted(runs, key=lambda r: (r.order, r.label))
    return sorted(runs, key=lambda r: r.label)


def find_regressions(runs: List[Run], threshold: float) -> List[str]:
    """The newest MEASURED run's metrics vs best-known-good over every
    earlier measured run; one finding per metric below threshold.
    Fewer than two measured runs ⇒ nothing to compare (outages never
    count as evidence either way)."""
    measured = [r for r in runs if r.status == "measured"]
    if len(measured) < 2:
        return []
    latest = measured[-1]
    earlier = measured[:-1]
    # Only the primary metric gates; the other TREND_METRICS trend
    # informationally (vs_baseline moves with the denominator model
    # class, MFU/GB/s only exist on real backends).
    name = PRIMARY_METRIC
    best = max(
        (r.metrics[name] for r in earlier if name in r.metrics),
        default=None,
    )
    got = latest.metrics.get(name)
    if best is None or got is None or best <= 0:
        return []
    if got >= threshold * best:
        return []
    return [
        f"{name}: {latest.label} measured {got:.4g}, "
        f"best-known-good {best:.4g} "
        f"({100.0 * got / best:.1f}% < {100.0 * threshold:.0f}% "
        "threshold)"
    ]


def render_report(runs: List[Run], threshold: float) -> str:
    lines: List[str] = []
    label_w = max(len("run"), max(len(r.label) for r in runs))
    lines.append("== bench trajectory ==")
    lines.append(
        f"{'run':<{label_w}}  {'status':<8}  {'dec/s':>9}  "
        f"{'vs_base':>8}  note"
    )
    for r in runs:
        dec = r.metrics.get("decisions_per_sec")
        vsb = r.metrics.get("vs_baseline")
        lines.append(
            f"{r.label:<{label_w}}  {r.status:<8}  "
            f"{(f'{dec:.3f}' if dec is not None else '-'):>9}  "
            f"{(f'{vsb:.3f}' if vsb is not None else 'null'):>8}  "
            f"{r.note}"
        )
    measured = [r for r in runs if r.status == "measured"]
    outages = [r for r in runs if r.status == "outage"]
    lines.append("")
    lines.append(
        f"{len(measured)} measured, {len(outages)} outage(s)"
        + (f" ({', '.join(r.label for r in outages)}) — excluded from "
           "regression analysis" if outages else "")
    )
    # Per-metric trend tables over measured runs only.
    for name, _ in TREND_METRICS:
        rows = [(r.label, r.metrics[name]) for r in measured
                if name in r.metrics]
        if not rows:
            continue
        best = max(v for _, v in rows)
        lines.append("")
        lines.append(f"-- {name} (best-known-good {best:.4g}) --")
        for label, value in rows:
            pct = 100.0 * value / best if best else 0.0
            lines.append(f"  {label:<{label_w}}  {value:>10.4g}  "
                         f"{pct:>6.1f}% of best")
    findings = find_regressions(runs, threshold)
    if findings:
        lines.append("")
        for f in findings:
            lines.append(f"REGRESSION: {f}")
    return "\n".join(lines)


def collect_paths(args: List[str]) -> List[str]:
    paths: List[str] = []
    for arg in args:
        if os.path.isdir(arg):
            paths.extend(sorted(glob.glob(os.path.join(arg, "BENCH_r*.json"))))
        else:
            paths.append(arg)
    return paths


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Merge BENCH_r*.json records into per-metric trend "
        "tables; outages (null vs_baseline / error payloads) are "
        "reported, never counted as regressions."
    )
    parser.add_argument("paths", nargs="+",
                        help="bench JSON files, or a directory to glob "
                        "BENCH_r*.json from")
    parser.add_argument("--threshold", type=float, default=0.7,
                        help="regression threshold as a fraction of "
                        "best-known-good (default 0.7)")
    parser.add_argument("--alert-out", metavar="PATH",
                        help="also append each regression as an "
                        "alert-shaped JSONL record (the "
                        "BCG_TPU_ALERT_EVENTS sink schema) so "
                        "cross-run perf regressions merge into one "
                        "scripts/alert_report.py timeline with "
                        "runtime alerts")
    args = parser.parse_args(argv)
    paths = collect_paths(args.paths)
    if not paths:
        print("bench_trajectory: no bench files found", file=sys.stderr)
        return 1
    runs = []
    for path in paths:
        try:
            runs.append(load_run(path))
        except (OSError, json.JSONDecodeError) as exc:
            print(f"bench_trajectory: cannot read {path}: {exc}",
                  file=sys.stderr)
            return 1
    runs = order_runs(runs)
    print(render_report(runs, args.threshold))
    findings = find_regressions(runs, args.threshold)
    for f in findings:
        print(f"BENCH REGRESSION: {f}", file=sys.stderr)
    if findings and args.alert_out:
        try:
            write_alert_records(args.alert_out, findings)
        except OSError as exc:
            print(f"bench_trajectory: cannot write {args.alert_out}: "
                  f"{exc}", file=sys.stderr)
    return 2 if findings else 0


def write_alert_records(path: str, findings: List[str]) -> None:
    """Append the rc-2 verdict in the BCG_TPU_ALERT_EVENTS sink shape
    (manifest header + one firing record per regression) — hand-rolled
    by value, NOT imported from bcg_tpu.obs.export: this script stays
    import-free so it runs on a laptop against scp'd files.  No
    resolved record is ever written: a cross-run perf regression stays
    firing on the alert_report timeline until a newer trajectory run
    clears it (by simply not re-emitting)."""
    now = time.time()
    with open(path, "a", encoding="utf-8") as fh:
        fh.write(json.dumps({
            "ts": now, "event": "manifest", "schema_version": 1,
            "run_id": "bench-trajectory", "kind": "bench",
        }) + "\n")
        for f in findings:
            fh.write(json.dumps({
                "ts": now, "event": "alert", "rule": "bench_regression",
                "severity": "page", "state": "firing", "kind": "trend",
                "value": None, "summary": f,
            }) + "\n")


if __name__ == "__main__":
    sys.exit(main())
