#!/usr/bin/env python
"""Developer entry point for the static analyzer (bcg_tpu.analysis).

``python scripts/lint.py``          — whole-repo run (same as
                                      ``python -m bcg_tpu.analysis``)
``python scripts/lint.py --diff``   — findings restricted to files
                                      changed vs main (fast pre-commit)
``python scripts/lint.py PATH...``  — explicit files/dirs

Any remaining ``python -m bcg_tpu.analysis`` flags pass through
(``--no-baseline``, ``--json`` — each finding tagged ``new`` or
``baselined`` — ``--show-baselined``, ``--locks`` for the whole-program
thread-root × lock report).
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def changed_files(base: str = "main") -> list:
    """Python files changed vs the merge-base with ``base`` (falls back
    to HEAD~1, then to uncommitted changes only)."""
    candidates = []
    for ref in (base, "HEAD~1"):
        try:
            mb = subprocess.run(
                ["git", "merge-base", "HEAD", ref],
                cwd=REPO, capture_output=True, text=True, check=True,
            ).stdout.strip()
            candidates = [mb]
            break
        except subprocess.CalledProcessError:
            continue
    # With no usable merge-base, diff against HEAD (staged + unstaged);
    # a bare `git diff` would silently skip staged modifications.
    diff_args = ["git", "diff", "--name-only", candidates[0] if candidates else "HEAD"]
    try:
        out = subprocess.run(
            diff_args, cwd=REPO, capture_output=True, text=True, check=True
        ).stdout
    except subprocess.CalledProcessError:
        out = subprocess.run(
            ["git", "diff", "--name-only"],
            cwd=REPO, capture_output=True, text=True, check=True,
        ).stdout
    # `git diff` never lists brand-new (untracked) files — exactly the
    # ones a pre-commit check most needs to see.
    out += subprocess.run(
        ["git", "ls-files", "--others", "--exclude-standard"],
        cwd=REPO, capture_output=True, text=True, check=True,
    ).stdout
    files = []
    for line in out.splitlines():
        line = line.strip()
        if line.endswith(".py"):
            full = os.path.join(REPO, line)
            if os.path.exists(full) and not line.startswith("tests/"):
                files.append(full)
    return files


def main() -> int:
    args = sys.argv[1:]
    if "--diff" in args:
        args.remove("--diff")
        files = changed_files()
        if not files:
            print("lint --diff: no changed python files vs main",
                  file=sys.stderr)
            return 0
        args = files + args
    from bcg_tpu.analysis.__main__ import main as analysis_main

    return analysis_main(args)


if __name__ == "__main__":
    sys.exit(main())
