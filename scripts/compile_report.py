#!/usr/bin/env python
"""Compile-cost report: compile time by entry + retraces by cause.

``python scripts/compile_report.py FILE [--events CAUSES.jsonl]``

``FILE`` is anything that carries the flat counter registry the
compile observer (``bcg_tpu/obs/compile.py``, ``BCG_TPU_COMPILE_OBS``)
feeds: a Chrome trace export (``otherData.counters``), a bench JSON
(``extra.counters`` on success, top-level ``counters`` on error, or the
driver-wrapped ``parsed`` form the BENCH_r*.json records use), or a
plain ``{name: value}`` snapshot dump.  ``--events`` additionally reads
the retrace-cause JSONL stream (``BCG_TPU_COMPILE_OBS=<path>``) for the
per-argument cause table the counters alone cannot carry.

Printed hottest-first:

* **compile time by entry** — compiles / retraces / total / p50 / p95
  milliseconds per jit entry, rebuilt from the
  ``engine.compile_ms.<entry>`` histogram flats and the
  ``engine.compile.<entry>`` / ``engine.retrace.<entry>`` counters;
* **retraces by cause** — the ``engine.retrace_cause.<kind>`` taxonomy
  counts (shape / dtype / static_knob / path / arity), plus, with
  ``--events``, the concrete ``entry: arg old→new`` lines;
* a cumulative footer (first-compile vs retrace vs census-AOT
  milliseconds, trace-cache population).

Self-contained — no bcg_tpu import — so a bench JSON copied off a TPU
host can be read anywhere; the in-process equivalent is
``bcg_tpu.obs.compile.summary()``.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import Counter as TallyCounter
from typing import Dict, List, Optional, Tuple

COMPILE_MS_PREFIX = "engine.compile_ms."
CAUSE_PREFIX = "engine.retrace_cause."


def extract_counters(data) -> Dict[str, float]:
    """The flat counter dict inside any of the supported file shapes
    (first match wins, searched shallowly so an unrelated nested
    'counters' key cannot shadow the real one)."""
    if not isinstance(data, dict):
        return {}
    for candidate in (
        (data.get("otherData") or {}).get("counters"),   # trace export
        (data.get("extra") or {}).get("counters"),       # bench success
        data.get("counters"),                            # bench error
        (data.get("parsed") or {}).get("counters"),      # driver wrap
        ((data.get("parsed") or {}).get("extra") or {}).get("counters"),
    ):
        if isinstance(candidate, dict):
            return candidate
    # Plain snapshot dump: every value numeric, dotted names.
    if data and all(
        isinstance(v, (int, float)) and "." in k for k, v in data.items()
    ):
        return data
    return {}


def _parse_bound(label: str) -> float:
    """``le_`` label -> float bound (``25`` -> 25.0, ``2_5`` -> 2.5 —
    the registry's bound_label encoding, reimplemented to stay
    import-free)."""
    return float(label.replace("_", "."))


def _quantile(buckets: List[Tuple[float, float]], total: float,
              q: float) -> float:
    """Prometheus histogram_quantile over cumulative (bound, count)
    pairs (trace_report.py's form, kept import-free here too)."""
    if total <= 0:
        return 0.0
    target = q * total
    prev_bound, prev_cum = 0.0, 0.0
    for bound, cum in buckets:
        if cum >= target and cum > prev_cum:
            frac = (target - prev_cum) / (cum - prev_cum)
            return prev_bound + (bound - prev_bound) * max(0.0, min(1.0, frac))
        prev_bound, prev_cum = bound, cum
    return buckets[-1][0] if buckets else 0.0


def compile_entries(counters: Dict[str, float]) -> Dict[str, Dict[str, float]]:
    """{entry: {count, total_ms, p50_ms, p95_ms, compiles, retraces}}
    rebuilt from the compile_ms histogram flats + compile/retrace
    counters."""
    out: Dict[str, Dict[str, float]] = {}
    buckets: Dict[str, List[Tuple[float, float]]] = {}
    for name, value in counters.items():
        if not name.startswith(COMPILE_MS_PREFIX):
            continue
        rest = name[len(COMPILE_MS_PREFIX):]
        if ".bucket.le_" in rest:
            entry, label = rest.split(".bucket.le_", 1)
            buckets.setdefault(entry, []).append((_parse_bound(label), value))
        elif rest.endswith(".sum"):
            out.setdefault(rest[:-len(".sum")], {})["total_ms"] = float(value)
        elif rest.endswith(".count"):
            out.setdefault(rest[:-len(".count")], {})["count"] = int(value)
    for entry, row in out.items():
        ordered = sorted(buckets.get(entry, []))
        total = row.get("count", 0)
        row["p50_ms"] = _quantile(ordered, total, 0.50)
        row["p95_ms"] = _quantile(ordered, total, 0.95)
        row["compiles"] = int(counters.get(f"engine.compile.{entry}", 0))
        row["retraces"] = int(counters.get(f"engine.retrace.{entry}", 0))
    return out


def compile_time_table(counters: Dict[str, float]) -> str:
    """'compile time by entry' table (hottest first by total ms), or ''
    when the export carries no compile observability."""
    rows = compile_entries(counters)
    if not rows:
        return ""
    ordered = sorted(rows.items(), key=lambda kv: -kv[1].get("total_ms", 0.0))
    name_w = max(len("jit entry"), max(len(e) for e in rows))
    lines = ["== compile time by entry (engine.compile_ms.*) =="]
    lines.append(
        f"{'jit entry':<{name_w}}  {'compiles':>8}  {'retraces':>8}  "
        f"{'total_ms':>10}  {'p50_ms':>9}  {'p95_ms':>9}"
    )
    for entry, row in ordered:
        lines.append(
            f"{entry:<{name_w}}  {row.get('compiles', 0):>8}  "
            f"{row.get('retraces', 0):>8}  "
            f"{row.get('total_ms', 0.0):>10.1f}  "
            f"{row.get('p50_ms', 0.0):>9.1f}  {row.get('p95_ms', 0.0):>9.1f}"
        )
    return "\n".join(lines)


def cause_table(counters: Dict[str, float],
                events: Optional[List[dict]] = None) -> str:
    """'retraces by cause' table (taxonomy counts, hottest first), with
    the concrete per-argument lines when the JSONL event stream is
    given; '' when the export carries neither."""
    kinds = sorted(
        ((k[len(CAUSE_PREFIX):], int(v)) for k, v in counters.items()
         if k.startswith(CAUSE_PREFIX)),
        key=lambda kv: (-kv[1], kv[0]),
    )
    details: TallyCounter = TallyCounter()
    for rec in events or []:
        if rec.get("event") != "retrace_cause":
            continue
        details[
            f"{rec.get('entry', '?')}: {rec.get('arg', '?')} "
            f"{rec.get('old')}→{rec.get('new')} "
            f"({rec.get('cause', '?')})"
        ] += 1
    if not kinds and not details:
        return ""
    lines = ["== retraces by cause (engine.retrace_cause.*) =="]
    if kinds:
        name_w = max(len("cause"), max(len(k) for k, _ in kinds))
        lines.append(f"{'cause':<{name_w}}  {'retraces':>8}")
        for kind, count in kinds:
            lines.append(f"{kind:<{name_w}}  {count:>8}")
    if details:
        lines.append("")
        lines.append("-- cause records (from the JSONL stream) --")
        for line, count in sorted(details.items(),
                                  key=lambda kv: (-kv[1], kv[0])):
            lines.append(f"{count:>4}x  {line}")
    return "\n".join(lines)


def footer(counters: Dict[str, float]) -> str:
    first = counters.get("engine.compile_obs.first_compile_ms")
    retrace = counters.get("engine.compile_obs.retrace_ms")
    aot = counters.get("engine.compile_obs.aot_ms")
    entries = counters.get("engine.compile_obs.cache_entries")
    if first is None and entries is None:
        return ""
    return (
        f"cumulative: {float(first or 0):.1f} ms first-compile, "
        f"{float(retrace or 0):.1f} ms retrace, "
        f"{float(aot or 0):.1f} ms census-AOT; "
        f"{int(entries or 0)} trace-cache entr"
        f"{'y' if int(entries or 0) == 1 else 'ies'}"
    )


def load_events(path: str) -> List[dict]:
    """Parsed JSONL records (the manifest first line rides along and is
    ignored by the tables); truncated tail lines are tolerated — a live
    stream's last line may be mid-write."""
    records = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return records


def render_report(counters: Dict[str, float],
                  events: Optional[List[dict]] = None) -> str:
    sections = [
        compile_time_table(counters),
        cause_table(counters, events),
        footer(counters),
    ]
    body = "\n\n".join(s for s in sections if s)
    return body if body else (
        "no compile observability in this export — run with "
        "BCG_TPU_COMPILE_OBS=1 (bcg_tpu/obs/compile.py)"
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Compile time by entry + retraces by cause from a "
        "counters-bearing export (trace JSON, bench JSON, or a flat "
        "snapshot)."
    )
    parser.add_argument("file", help="trace/bench/snapshot JSON path")
    parser.add_argument("--events", default=None,
                        help="retrace-cause JSONL stream "
                        "(BCG_TPU_COMPILE_OBS=<path>)")
    args = parser.parse_args(argv)
    try:
        with open(args.file) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError) as exc:
        print(f"compile_report: cannot read {args.file}: {exc}",
              file=sys.stderr)
        return 1
    events = None
    if args.events:
        try:
            events = load_events(args.events)
        except OSError as exc:
            print(f"compile_report: cannot read {args.events}: {exc}",
                  file=sys.stderr)
            return 1
    print(render_report(extract_counters(data), events))
    return 0


if __name__ == "__main__":
    sys.exit(main())
