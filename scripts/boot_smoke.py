#!/usr/bin/env python
"""Abstract boot smoke: eval_shape-boot EVERY model preset — 14B/32B
included — through the born-sharded init plan and the HBM accounting,
failing on any sharding/budget inconsistency WITHOUT materializing a
single weight.

Tier-1-safe (CPU, seconds): the round-5 14B hardware failure was a boot
problem that no CPU test could see because every boot-path check
materialized weights at test scale only.  This smoke runs the exact
abstract machinery the real boot uses — ``transformer.param_plan`` +
``param_sharding`` + ``loader.boot_peak_report`` +
``sharding.kv_cache_bytes_per_device`` — at FLAGSHIP shapes, so a spec
or layout change that would brick a 14B boot fails here first.

Checks, per (preset, mesh, quantization) combination:

1.  every plan leaf (and quantized sub-leaf) has a placeable sharding —
    ``shard_shape`` raises on a sharded dim that doesn't divide its
    mesh axis, which is exactly what the real per-leaf jit would hit;
2.  the analytic boot peak obeys the born-sharded contract:
    peak-per-device <= final tree + one leaf-group (the larger of the
    biggest stacking group and the biggest single-leaf init transient);
3.  under a multi-device mesh, large 2-D dense leaves actually shard
    (no silent full-precision replica of embed/wq/w_gate at init);
4.  the KV capacity accounting is self-consistent: summing
    ``kv_cache_bytes_per_device`` over the mesh equals the global cache
    bytes times the replication factor of the axes that did NOT engage
    (divisibility guards), for engaged, dp-bypass, and
    guard-failing shapes.

Run standalone (``python scripts/boot_smoke.py``) or through
``tests/test_boot_smoke.py``.
"""

from __future__ import annotations

import os
import sys


def _ensure_cpu_mesh() -> None:
    """Force an 8-virtual-device CPU backend BEFORE jax initializes
    (same dance as tests/conftest.py: the axon sitecustomize overrides
    JAX_PLATFORMS, so the config.update is required too)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except (ValueError, AttributeError):
        # jax version without the knob: the env vars above still apply.
        pass


def check_preset(name: str, mesh, quantization) -> list:
    """All boot-path inconsistencies for one (preset, mesh, quant)
    combination — empty list means the abstract boot is sound."""
    import jax
    import jax.numpy as jnp

    from bcg_tpu.models.configs import MODEL_SPECS
    from bcg_tpu.models.loader import boot_peak_report
    from bcg_tpu.models.quantize import quantize_leaf_transform
    from bcg_tpu.models.transformer import init_kv_cache, param_plan
    from bcg_tpu.parallel.sharding import (
        kv_cache_bytes_per_device,
        kv_cache_tree_sharding,
        param_sharding,
    )

    spec = MODEL_SPECS[name]
    problems = []
    transform = (
        quantize_leaf_transform(spec, quantization) if quantization else None
    )

    # --- 1. every leaf (incl. quantized sub-leaves) places cleanly ----
    for logical, kind, shape in param_plan(spec):
        src = jax.ShapeDtypeStruct(
            shape, jnp.float32 if kind == "dense" else jnp.bfloat16
        )

        def _make(w, _logical=logical, _kind=kind):
            w = w.astype(jnp.bfloat16)
            if transform is not None and _kind == "dense":
                return transform(_logical, w)
            return w

        out = jax.eval_shape(_make, src)
        subleaves = (
            {f"{logical}.{sub}": s for sub, s in out.items()}
            if isinstance(out, dict)
            else {logical: out}
        )
        for sub_logical, struct in subleaves.items():
            if mesh is None:
                continue
            sh = param_sharding(sub_logical, spec, mesh)
            try:
                sh.shard_shape(struct.shape)
            except Exception as e:
                problems.append(
                    f"{name}: {sub_logical} {struct.shape} does not place "
                    f"under {sh.spec}: {e}"
                )

    if problems:
        # Unplaceable leaves would make the accounting below raise the
        # same divisibility error less legibly — report them as is.
        return problems

    # --- 2. + 3. analytic boot peak obeys the born-sharded contract ---
    report = boot_peak_report(spec, mesh=mesh, quantization=quantization)
    headroom = max(
        report["max_leaf_group_bytes"], report["max_init_transient_bytes"]
    )
    if report["peak_bytes_per_device"] > (
        report["final_bytes_per_device"] + headroom
    ):
        problems.append(
            f"{name}: boot peak {report['peak_bytes_per_device']} exceeds "
            f"final tree + one leaf-group "
            f"({report['final_bytes_per_device']} + {headroom})"
        )
    if mesh is not None and mesh.shape.get("tp", 1) > 1:
        # Weights shard over tp only (dp/sp replicate them by design),
        # so the no-unsharded-full-precision-leaf contract is checkable
        # exactly when tp engages: the biggest init transient must be a
        # SHARD, not the whole fp32 embed.
        full_embed_fp32 = spec.vocab_size * spec.hidden_size * 4
        if report["max_init_transient_bytes"] >= full_embed_fp32:
            problems.append(
                f"{name}: init transient "
                f"{report['max_init_transient_bytes']} is a full "
                f"unsharded fp32 leaf ({report['max_init_transient_leaf']})"
                " — born-sharded contract broken"
            )

    # --- 4. KV capacity accounting self-consistency --------------------
    if mesh is not None:
        for B, S, quant_kv in ((8, 1024, False), (3, 1024, False),
                               (8, 1021, True)):
            shapes = jax.eval_shape(
                lambda: init_kv_cache(spec, B, S, quantized=quant_kv)
            )
            per_dev = kv_cache_bytes_per_device(
                mesh, shapes, quantized=quant_kv
            )
            shardings = kv_cache_tree_sharding(
                mesh, shapes, quantized=quant_kv
            )
            expected = 0
            for leaf, sh in zip(
                jax.tree.leaves(shapes),
                jax.tree.leaves(
                    shardings, is_leaf=lambda s: hasattr(s, "shard_shape")
                ),
            ):
                engaged = 1
                for ax in sh.spec:
                    if ax is not None:
                        engaged *= mesh.shape[ax]
                expected += (
                    leaf.size * leaf.dtype.itemsize
                ) // engaged
            if per_dev != expected:
                problems.append(
                    f"{name}: kv_cache_bytes_per_device(B={B}, S={S}, "
                    f"int8={quant_kv}) = {per_dev}, engaged-axes "
                    f"expectation {expected}"
                )
    return problems


def run_all(verbose: bool = True) -> list:
    """Smoke every preset under representative mesh/quantization
    combinations; returns the accumulated problem list."""
    import jax

    from bcg_tpu.models.configs import (
        LARGE_MODEL_PARAMS, MODEL_SPECS, XL_MODEL_PARAMS,
    )
    from bcg_tpu.parallel.mesh import build_mesh

    n_dev = len(jax.devices())
    meshes = [("single", None)]
    if n_dev >= 8:
        meshes += [
            ("tp8", build_mesh(dp=1, tp=8, sp=1)),
            ("dp8", build_mesh(dp=8, tp=1, sp=1)),
            ("dp2tp2sp2", build_mesh(dp=2, tp=2, sp=2)),
        ]
    problems = []
    for name, spec in sorted(MODEL_SPECS.items()):
        # Quantization per the bench's size-class gates, plus bf16 so
        # both materialization formats stay abstract-bootable.
        if spec.param_count >= XL_MODEL_PARAMS:
            quants = ["int4", "int8"]
        elif spec.param_count >= LARGE_MODEL_PARAMS:
            quants = ["int8", None]
        else:
            quants = [None, "int8"]
        for mesh_name, mesh in meshes:
            for quant in quants:
                got = check_preset(name, mesh, quant)
                problems += got
                if verbose:
                    status = "FAIL" if got else "ok"
                    print(
                        f"boot_smoke: {name:45s} mesh={mesh_name:10s} "
                        f"quant={str(quant):5s} {status}"
                    )
    return problems


def main(argv=None) -> int:
    _ensure_cpu_mesh()
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    problems = run_all()
    if problems:
        print(f"\nboot_smoke: {len(problems)} problem(s):")
        for p in problems:
            print(f"  - {p}")
        return 1
    print("boot_smoke: all presets abstract-boot cleanly")
    return 0


if __name__ == "__main__":
    sys.exit(main())
