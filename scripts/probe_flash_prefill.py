#!/usr/bin/env python
"""Lower and validate the Pallas flash-prefill kernel on the TPU.

bench_14b's first attempt crashed in its FIRST prefill compile (remote
helper HTTP 500 / exit 1) with the W4 kernel already disabled, leaving
two suspects: the int8 decode kernels at GQA group 5 (now excluded by
the engine's group guard) and this flash kernel at 14B dims (H=40 —
untested on hardware; 1B/8B ran H=16/32).  This probe lowers the kernel
at the chunked-prefill shapes each preset actually serves and checks it
against the pure-JAX blockwise reference, so the crasher is identified
by name instead of inferred from a failed 90-minute bench.

Fails off-TPU (nothing would be validated).  Prints
"flash-prefill-probe OK" when all cases pass.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from bcg_tpu.ops.attention import blockwise_attention, flash_attention

# (name, B, T, S, H, Hkv, Dh): T = chunk length (prefill_chunk for the
# large class), S = T + cached history the chunk attends.
CASES = [
    ("1b-full-prefill", 4, 1024, 1024, 16, 8, 128),
    ("8b-chunk", 10, 512, 2048, 32, 8, 128),
    ("14b-chunk", 10, 512, 2048, 40, 8, 128),
    ("14b-first-chunk", 10, 512, 512, 40, 8, 128),
]


def main() -> None:
    backend = jax.default_backend()
    print("backend:", backend)
    if backend != "tpu":
        print("flash-prefill-probe FAILED: accelerator unavailable "
              "(backend is not tpu; nothing validated)")
        raise SystemExit(1)
    rng = np.random.default_rng(0)
    ok = True
    for name, B, T, S, H, Hkv, Dh in CASES:
        q = jnp.asarray(rng.standard_normal((B, T, H, Dh)) * 0.3, jnp.bfloat16)
        k = jnp.asarray(rng.standard_normal((B, S, Hkv, Dh)) * 0.3, jnp.bfloat16)
        v = jnp.asarray(rng.standard_normal((B, S, Hkv, Dh)) * 0.3, jnp.bfloat16)
        # Causal-with-history mask plus some padding holes, like the
        # chunk path builds (transformer.prefill_chunk_at).
        hist = S - T
        causal = np.tril(np.ones((T, T), bool))
        mask_np = np.concatenate(
            [np.ones((T, hist), bool), causal], axis=1
        )[None].repeat(B, axis=0)
        mask_np[:, :, : max(hist // 8, 0)] = False  # left-pad holes
        mask = jnp.asarray(mask_np)
        scale = Dh ** -0.5
        try:
            got = np.asarray(
                flash_attention(q, k, v, mask, scale), dtype=np.float32
            )
            want = np.asarray(
                blockwise_attention(q, k, v, mask, scale), dtype=np.float32
            )
            err = float(np.max(np.abs(got - want)))
            denom = float(np.max(np.abs(want))) + 1e-9
            rel = err / denom
            good = rel < 5e-2
            if not good:
                ok = False
            print(f"  {name:<18s} max|d|={err:.4f} rel={rel:.3e} "
                  f"{'OK' if good else 'MISMATCH'}")
        except Exception as exc:  # noqa: BLE001 — a probe reports, not crashes
            ok = False
            print(f"  {name:<18s} FAILED: "
                  f"{type(exc).__name__}: {str(exc)[:200]}")
    print("flash-prefill-probe OK" if ok else "flash-prefill-probe FAILED")
    raise SystemExit(0 if ok else 1)


if __name__ == "__main__":
    main()
