#!/usr/bin/env python
"""Lower and validate the int8 decode-attention kernels on the TPU.

The all-heads int8 kernels (ops/decode_attention.py, round-3 rework:
grid (B, nS) with an in-kernel Hkv loop) are interpret-mode tested on
CPU but have never lowered on real hardware.  This probe runs both the
single-step and fast-forward chunk kernels at bench-1b and 8B game
shapes against a pure-XLA dequant-attention reference, so a Mosaic
lowering or miscompile problem surfaces as a named failure instead of
a crash (or silent corruption) inside the queued int8-KV / 8B benches.

Fails off-TPU (nothing would be validated).  Prints
"int8-decode-probe OK" when all cases pass.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from bcg_tpu.ops.decode_attention import (
    chunk_decode_attention,
    decode_attention,
    dequantize_kv,
    quantize_kv,
)

# (name, B, H, Hkv, Dh, S).  S values cover BOTH kernel block
# configurations: 2048/4096 divide ALIGN_S=1024 so they compile the
# block-1024 path the engine actually serves (it aligns the int8 cache
# to ALIGN_S), while 3584 exercises the block-512 fallback pick.
CASES = [
    ("1b-shapes", 10, 16, 8, 128, 2048),
    ("8b-shapes", 10, 32, 8, 128, 4096),
    ("block512-path", 10, 32, 8, 128, 3584),
]

# INFORMATIONAL cases: validated-if-they-pass, but failures do NOT gate
# the probe's verdict — the watcher's INT8_FALLBACK must never disable
# the kernel for the VALIDATED group-2/4 configs because an
# experimental geometry regressed.  14B (H=40, Hkv=8 -> GQA group 5):
# the wrapper now pads query rows to the next power of two
# (ops/decode_attention.py), so the kernel sees rows=8 — a validated
# count — but the padded dispatch itself has not run on hardware yet;
# the engine's GQA group guard keeps 14B on the XLA dequant fallback
# until this case records an OK.
INFO_CASES = [
    ("14b-group5-padded", 10, 40, 8, 128, 4096),
]


def _reference(q, kd, vd, mask, scale):
    """Stock masked softmax attention on the dequantized cache.

    q [B, H, Dh]; kd/vd [B, Hkv, S, Dh] f32; mask [B, S].
    """
    B, H, Dh = q.shape
    Hkv = kd.shape[1]
    group = H // Hkv
    qg = q.reshape(B, Hkv, group, Dh).astype(jnp.float32)
    scores = jnp.einsum("bhgd,bhsd->bhgs", qg, kd) * scale
    scores = jnp.where(mask[:, None, None, :], scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhgs,bhsd->bhgd", p, vd)
    return out.reshape(B, H, Dh)


def main() -> None:
    backend = jax.default_backend()
    print("backend:", backend)
    if backend != "tpu":
        # "unavailable" keeps the watcher's availability triage retrying
        # (a tunnel can die between the watcher's probe and this step,
        # silently falling JAX back to CPU) instead of burning strikes.
        print("int8-decode-probe FAILED: accelerator unavailable "
              "(backend is not tpu; nothing validated)")
        raise SystemExit(1)
    rng = np.random.default_rng(0)
    ok = True
    for name, B, H, Hkv, Dh, S in CASES + INFO_CASES:
        gating = (name, B, H, Hkv, Dh, S) in CASES
        q = jnp.asarray(rng.standard_normal((B, H, Dh)) * 0.3, jnp.bfloat16)
        k_bf = jnp.asarray(rng.standard_normal((B, Hkv, S, Dh)) * 0.3, jnp.float32)
        v_bf = jnp.asarray(rng.standard_normal((B, Hkv, S, Dh)) * 0.3, jnp.float32)
        k_i8, k_s = quantize_kv(k_bf)
        v_i8, v_s = quantize_kv(v_bf)
        valid = rng.random((B, S)) > 0.2
        valid[:, -1] = True
        mask = jnp.asarray(valid)
        scale = Dh ** -0.5

        kd = dequantize_kv(k_i8, k_s)
        vd = dequantize_kv(v_i8, v_s)
        want = np.asarray(_reference(q, kd, vd, mask, scale), dtype=np.float32)

        for kind in ("step", "chunk"):
            try:
                if kind == "step":
                    got = decode_attention(
                        q, k_i8, v_i8, mask, scale, k_scale=k_s, v_scale=v_s
                    )
                    got = np.asarray(got, dtype=np.float32)
                    ref = want
                else:
                    K = 4
                    qk = jnp.asarray(
                        rng.standard_normal((B, K, H, Dh)) * 0.3, jnp.bfloat16
                    )
                    maskk = jnp.broadcast_to(mask[:, None, :], (B, K, S))
                    got = chunk_decode_attention(
                        qk, k_i8, v_i8, maskk, scale, k_scale=k_s, v_scale=v_s
                    )
                    got = np.asarray(got, dtype=np.float32)
                    ref = np.stack(
                        [np.asarray(_reference(qk[:, i], kd, vd, mask, scale))
                         for i in range(K)], axis=1,
                    )
                err = float(np.max(np.abs(got - ref)))
                denom = float(np.max(np.abs(ref))) + 1e-9
                rel = err / denom
                good = rel < 5e-2  # bf16 q + f32-accum reorder tolerance
                if not good and gating:
                    ok = False
                tag = "OK" if good else "MISMATCH"
                if not gating:
                    tag = "info-" + tag
                print(f"  {name}/{kind:<6s} max|d|={err:.4f} rel={rel:.3e} "
                      f"{tag}")
            except Exception as exc:  # noqa: BLE001 — a probe reports, not crashes
                if gating:
                    ok = False
                print(f"  {name}/{kind:<6s} "
                      f"{'FAILED' if gating else 'info-FAILED'}: "
                      f"{type(exc).__name__}: {str(exc)[:200]}")
    print("int8-decode-probe OK" if ok else "int8-decode-probe FAILED")
    raise SystemExit(0 if ok else 1)


if __name__ == "__main__":
    main()
