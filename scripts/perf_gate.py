#!/usr/bin/env python
"""Hermetic perf-regression gate: counter-derived metrics vs banded
baselines, CPU-only.

BENCH_r02-r05 lost an entire benchmark trajectory to accelerator-attach
outages — wall-clock on flaky hardware cannot gate anything.  This gate
re-derives the perf story from COUNTERS, which are exact on any
backend:

* ``engine`` scenario — a tiny real ``JaxEngine`` (``bcg-tpu/
  tiny-test``) runs the guided-JSON decision benchmark twice (plain and
  speculative): device decode iterations per decision, the speculative
  step-reduction ratio, the draft acceptance rate, and ZERO
  steady-state retraces (counter deltas over a warm repeat call).
* ``serve`` scenario — a scripted FakeEngine serving run (16 concurrent
  requests against one scheduler bucket, spec mirror on): completion
  fraction, engine errors, batch-merge rows per dispatch, and the
  mirrored draft acceptance rate.
* ``hlo`` scenario — delegates to ``scripts/hlo_census.py``'s drift
  check (kernel counts per jit entry vs ``hlo_baseline.json``) and
  gates on zero findings.

Every measured metric must have a justified entry in
``perf_baseline.json`` (same load-bearing idiom as
``lint_baseline.json``: an unbaselined metric is itself a failure, so
deleting an entry RESURFACES its check rather than silencing it; a
baseline entry the scenarios no longer produce is a stale-entry
failure).  Bounds are tolerance-banded (``op``: ``min``/``max``/
``range`` with ``tol_rel``/``tol_abs``); a regression failure names the
metric, the measured value, the violated bound, and the entry's reason.

Exit status: 0 = green; 2 = regression/drift (composes with
``set -o pipefail`` harnesses); 1 = usage error.  Tier-1 runs the same
comparisons in-process (``tests/test_perf_gate.py``).

Usage:
    python scripts/perf_gate.py                    # all scenarios
    python scripts/perf_gate.py --scenarios serve,engine
    python scripts/perf_gate.py --update-baseline  # regenerate (keeps reasons)
    python scripts/perf_gate.py --inject-regression spec-off   # self-test
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# "alerts" stays LAST: its oracle arm resets the counter registry to
# kill absolute-gauge leftovers (headroom, heartbeats, stragglers)
# that earlier scenarios legitimately leave behind.
SCENARIOS = ("serve", "engine", "paged", "sampler", "int4", "consensus",
             "fleet", "hostsync", "megaround", "compile", "sweep", "chaos",
             "scenarios", "hlo", "alerts")
REGRESSIONS = ("none", "spec-off", "fail-rows", "events-off",
               "straggler-off", "hostsync-off", "compile-off",
               "fairness-off", "chaos-off", "scenarios-off",
               "alerts-off")

DECISION = {
    "type": "object",
    "properties": {
        "internal_strategy": {"type": "string", "minLength": 1, "maxLength": 25},
        "value": {"type": "integer", "minimum": 0, "maximum": 50},
        "public_reasoning": {"type": "string", "minLength": 1, "maxLength": 25},
    },
    "required": ["internal_strategy", "value", "public_reasoning"],
    "additionalProperties": False,
}
VOTE = {
    "type": "object",
    "properties": {"decision": {"type": "string", "enum": ["stop", "continue"]}},
    "required": ["decision"],
    "additionalProperties": False,
}


def baseline_path() -> str:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(root, "perf_baseline.json")


def _force_cpu() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")


# ------------------------------------------------------------- scenarios
def run_serve_scenario(inject: str = "none") -> Dict[str, float]:
    """Scripted FakeEngine serving run: 2 waves x 8 threads x 2-row
    guided requests against a 16-row bucket with a generous linger, so
    full-bucket merges dominate regardless of host load.  The spec
    mirror (BCG_TPU_SPEC=1) makes the hermetic run carry a realistic
    draft-acceptance profile."""
    from bcg_tpu.engine.fake import FakeEngine
    from bcg_tpu.obs import counters as obs_counters
    from bcg_tpu.serve.scheduler import Scheduler

    # Save/restore needs the RAW value (None vs ""), not the parsed
    # bool — the registry accessors cannot round-trip "was unset".
    prior_spec = os.environ.get("BCG_TPU_SPEC")  # lint: ignore[BCG-ENV-RAW]
    os.environ["BCG_TPU_SPEC"] = "0" if inject == "spec-off" else "1"
    try:
        engine = FakeEngine(
            seed=0, policy="consensus",
            fail_first_n_calls=(10**6 if inject == "fail-rows" else 0),
        )
        sched = Scheduler(
            engine, linger_ms=400, bucket_rows=16,
            max_queue_rows=4096, deadline_ms=0, strict_admission=False,
        )
        before = obs_counters.snapshot()
        payload = [
            ("agent system prompt",
             "Round 2. agent_1 value: 17. agent_2 value: 17. "
             "Your current value: 17. Decide.",
             DECISION),
        ] * 2
        errors: List[BaseException] = []
        row_counts = {"rows": 0, "error_rows": 0}
        count_lock = threading.Lock()

        def one_request():
            try:
                out = sched.submit_and_wait(
                    ("json",), list(payload), [0.0] * 2, [64] * 2
                )
                bad = sum(
                    1 for r in out if not isinstance(r, dict) or "error" in r
                )
                with count_lock:
                    row_counts["rows"] += len(out)
                    row_counts["error_rows"] += bad
            except BaseException as e:  # collected, raised below
                errors.append(e)

        for _wave in range(2):
            threads = [
                threading.Thread(target=one_request) for _ in range(8)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        snap = sched.snapshot()
        sched.close()
        moved = obs_counters.delta(before)
    finally:
        if prior_spec is None:
            os.environ.pop("BCG_TPU_SPEC", None)
        else:
            os.environ["BCG_TPU_SPEC"] = prior_spec
    if errors:
        raise errors[0]
    drafted = moved.get("engine.spec.drafted", 0)
    accepted = moved.get("engine.spec.accepted", 0)
    dispatches = max(1, snap["dispatches"])
    return {
        "serve.completed_fraction": snap["completed"] / max(1, snap["submitted"]),
        "serve.engine_errors": snap["engine_errors"],
        "serve.error_row_fraction": (
            row_counts["error_rows"] / max(1, row_counts["rows"])
        ),
        "serve.rows_per_dispatch": snap["dispatched_rows"] / dispatches,
        "serve.spec_acceptance_rate": accepted / drafted if drafted else 0.0,
    }


def run_engine_scenario(inject: str = "none") -> Dict[str, float]:
    """Tiny real-engine decision benchmark, plain vs speculative, at
    temperature 0 (fully deterministic: fixed weights, fixed prompts) —
    the counter-derived core of what BENCH measures on hardware."""
    _force_cpu()
    from bcg_tpu.config import EngineConfig
    from bcg_tpu.engine.jax_engine import JaxEngine
    from bcg_tpu.obs import counters as obs_counters

    prompts = [
        ("honest agent system prompt", "Round 3: propose a value", DECISION),
        ("byzantine agent system prompt", "Round 3: vote now", VOTE),
        ("honest agent system prompt", "Round 4: propose a value", DECISION),
    ]

    def cfg(**kw):
        return EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test",
            max_model_len=2048, **kw,
        )

    std = JaxEngine(cfg())
    spec = JaxEngine(cfg(spec_decode=(inject != "spec-off")))
    try:
        r_std = std.batch_generate_json(prompts, temperature=0.0, max_tokens=80)
        steps_std = std.total_decode_steps
        before = obs_counters.snapshot()
        r_spec = spec.batch_generate_json(prompts, temperature=0.0, max_tokens=80)
        steps_spec = spec.total_decode_steps
        moved = obs_counters.delta(before)
        # Steady state: an identical-shape repeat call may compile
        # NOTHING new — the retrace counters must not move.
        before_warm = obs_counters.snapshot()
        spec.batch_generate_json(prompts, temperature=0.0, max_tokens=80)
        warm_moved = obs_counters.delta(before_warm)
    finally:
        std.shutdown()
        spec.shutdown()
    bad = sum(1 for r in r_std + r_spec if not isinstance(r, dict) or "error" in r)
    drafted = moved.get("engine.spec.drafted", 0)
    accepted = moved.get("engine.spec.accepted", 0)
    retraces = sum(
        v for k, v in warm_moved.items() if k.startswith("engine.retrace.")
    ) + sum(
        v for k, v in warm_moved.items() if k.startswith("engine.compile.")
    )
    decisions = len(prompts)
    return {
        "engine.decode_steps_per_decision": steps_spec / decisions,
        "engine.spec_step_reduction": 1.0 - steps_spec / max(1, steps_std),
        "engine.spec_acceptance_rate": accepted / drafted if drafted else 0.0,
        "engine.steady_state_retraces": retraces,
        "engine.error_rows": bad,
    }


def run_paged_scenario(inject: str = "none") -> Dict[str, float]:
    """Block-paged KV cache (engine/paged_kv.py) gates, all hermetic:

    * ``positions_real_per_agent_slope`` — per-game real prefill
      positions per agent at N=8 over N=2 (fresh engine per N, shared
      system prompt + per-agent tail).  Radix sharing prefills the
      shared prefix ONCE per game, so the ratio must stay well under 1
      (the superlinear-sharing acceptance assertion);
      ``positions_real_monotone`` is 1.0 iff strictly decreasing over
      N in {2, 4, 8}.
    * ``prefix_hit_rate`` — radix hit rate after a second round on a
      persistent engine (grown history extends round 1's chain).
    * ``greedy_parity_mismatches`` — paged vs dense greedy outputs on
      the same prompts (must be 0: token-identical by construction).
    * ``row_cap_gain`` — serve admission cap (derive_row_cap) of a
      paged engine over the dense worst-case provisioner at the SAME
      synthetic HBM budget; > 1 because the pool unifies the dense
      path's separate prefix reserve and needs no ALIGN_S padding.
    """
    _force_cpu()
    from bcg_tpu.config import EngineConfig
    from bcg_tpu.engine.jax_engine import JaxEngine
    from bcg_tpu.obs import counters as obs_counters
    from bcg_tpu.serve.scheduler import derive_row_cap

    def cfg(**kw):
        return EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test",
            max_model_len=2048, **kw,
        )

    shared_sys = (
        "You are an agent in a Byzantine consensus game. The rules are "
        "long and shared by every participant: propose integer values, "
        "exchange them with peers, and vote to stop once values converge "
        "within the consensus threshold. " * 3
    )
    per_agent: Dict[int, float] = {}
    for n_agents in (2, 4, 8):
        eng = JaxEngine(cfg(paged_kv=True))
        before = obs_counters.value("engine.prefill.positions_real")
        eng.batch_generate_json(
            [(shared_sys, f"You are agent_{i}. Round 1. Peers said 17. "
              "Decide.", VOTE) for i in range(n_agents)],
            temperature=0.0, max_tokens=24,
        )
        moved = obs_counters.value("engine.prefill.positions_real") - before
        per_agent[n_agents] = moved / n_agents
        eng.shutdown()
    monotone = float(per_agent[2] > per_agent[4] > per_agent[8])

    # Parity + hit rate: two rounds on ONE paged engine vs a dense twin.
    prompts = [
        (shared_sys + f" You are agent_{i}.", "Round 1. Decide.", DECISION)
        for i in range(3)
    ]
    dense = JaxEngine(cfg())
    paged = JaxEngine(cfg(paged_kv=True))
    try:
        mismatches = 0
        round1_batch = round1_dense = None
        for round_no in (1, 2):
            batch = [
                (s, f"Round {round_no}. Peers said 17. Decide.", sch)
                for s, _, sch in prompts
            ]
            r_d = dense.batch_generate_json(batch, temperature=0.0,
                                            max_tokens=48)
            r_p = paged.batch_generate_json(batch, temperature=0.0,
                                            max_tokens=48)
            mismatches += sum(1 for a, b in zip(r_d, r_p) if a != b)
            if round_no == 1:
                round1_batch, round1_dense = batch, r_d
        pool = paged.kv_pool_stats() or {}
        hit_rate = pool.get("prefix_hit_rate") or 0.0
    finally:
        dense.shutdown()
        paged.shutdown()

    # Impl parity: the fused Pallas kernel (interpret mode on this CPU
    # host) must reproduce the dense greedy output on the same batch —
    # the hermetic stand-in for the hardware kernel's token-identity
    # claim, gated 0 exact like the gather path's parity above.
    pallas = JaxEngine(cfg(paged_kv=True, paged_kv_impl="pallas"))
    try:
        r_k = pallas.batch_generate_json(round1_batch, temperature=0.0,
                                         max_tokens=48)
    finally:
        pallas.shutdown()
    pallas_mismatches = sum(
        1 for a, b in zip(round1_dense, r_k) if a != b
    )

    # Admission gain at one synthetic HBM budget.  The dense reserve
    # uses the boot formula's fraction WITHOUT its 256 MB large-model
    # floor (which would zero the dense budget at test-sized synthetic
    # limits and overstate the gain); the paged pool gets the same
    # budget with no separate reserve — the structural win under test.
    limit = 32 << 20
    dense = JaxEngine(cfg())
    dense._mem_limit = limit
    free = (dense.config.hbm_utilization * limit
            - dense._param_bytes_per_device)
    dense._prefix_budget = max(0, int(free * 0.25))
    dense_cap = derive_row_cap(dense) or 1
    # Size the equivalent pool at the block size the paged engine will
    # actually use (the config default) — a hardcoded 16 would silently
    # desync the comparison if the default ever moves (e.g. to the
    # Pallas kernel's 128).
    bs_blk = EngineConfig().kv_block_size
    block_bytes = bs_blk * dense._kv_slot_bytes * dense.spec.num_layers
    usable = max(64, int(free // block_bytes))
    dense.shutdown()
    paged = JaxEngine(cfg(paged_kv=True, kv_pool_blocks=usable + 1))
    paged_cap = derive_row_cap(paged) or 1
    paged.shutdown()

    if inject == "fail-rows":
        mismatches += 1  # self-test hook: provoke the parity gate
    return {
        "paged.positions_real_per_agent_slope": per_agent[8] / per_agent[2],
        "paged.positions_real_monotone": monotone,
        "paged.prefix_hit_rate": hit_rate,
        "paged.greedy_parity_mismatches": float(mismatches),
        "paged.pallas_parity_mismatches": float(pallas_mismatches),
        "paged.row_cap_gain": paged_cap / dense_cap,
    }


def run_sampler_scenario(inject: str = "none") -> Dict[str, float]:
    """Fused guided-sampling kernel (ops/guided_sampler.py, interpret
    mode on this CPU host — the same program hardware lowers) against
    the XLA masked-sampler reference, across ALL THREE decode-loop
    families on the greedy decision benchmark:

    * ``parity_mismatches`` — fused vs xla outputs per family (must be
      0 EXACT: greedy rows are token-identical by construction; the
      acceptance criterion's hermetic stand-in for the hardware
      kernel's claim).
    * ``fused_kernel_invocations`` — the fused engines' total kernel
      invocation count (one program per decode iteration); floored > 0
      so the parity gate can never pass vacuously with the kernel
      silently disengaged.
    """
    _force_cpu()
    from bcg_tpu.config import EngineConfig
    from bcg_tpu.engine.jax_engine import JaxEngine

    prompts = [
        ("honest agent system prompt", "Round 3: propose a value", DECISION),
        ("byzantine agent system prompt", "Round 3: vote now", VOTE),
    ]

    def cfg(**kw):
        return EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test",
            max_model_len=2048, **kw,
        )

    mismatches = 0
    fused_calls = 0
    for family_kw in ({}, {"decode_fast_forward": True},
                      {"spec_decode": True}):
        ref = JaxEngine(cfg(**family_kw))
        fused = JaxEngine(cfg(fused_sampler="pallas", **family_kw))
        try:
            r_ref = ref.batch_generate_json(prompts, temperature=0.0,
                                            max_tokens=64)
            r_fus = fused.batch_generate_json(prompts, temperature=0.0,
                                              max_tokens=64)
            mismatches += sum(1 for a, b in zip(r_ref, r_fus) if a != b)
            fused_calls += fused.sampler_stats()["fused_calls"]
        finally:
            ref.shutdown()
            fused.shutdown()
    if inject == "fail-rows":
        mismatches += 1  # self-test hook: provoke the parity gate
    return {
        "sampler.parity_mismatches": float(mismatches),
        "sampler.fused_kernel_invocations": float(fused_calls),
    }


def run_int4_scenario(inject: str = "none") -> Dict[str, float]:
    """Packed-int4 KV cache gates, all hermetic:

    * ``row_cap_gain`` — ``cap_for``-derived dense admission cap of an
      int4 engine over its int8 twin at the SAME synthetic HBM budget
      (min-banded >= 1.8: the packed slot is exactly half the int8
      slot — 2(Dh+4) vs Dh+4 bytes per kv head — so the cap doubles up
      to integer flooring).
    * ``pool_blocks_gain`` — paged-pool auto-sizing at the same
      synthetic budget (the serve-admission form of the same claim:
      admission caps come out measurably higher).
    * ``paged_parity_mismatches`` — int4 paged (fused kernel, interpret
      mode) vs int4 dense greedy outputs (0 exact: identical
      quantization, block paging is bit-preserving).
    * ``error_rows`` — every int4 decision/vote row parses as valid
      guided JSON (the decision benchmark staying within the
      established quantization tolerance; token-level drift vs bf16 is
      tier-1's tolerance test, not a gate band).
    """
    _force_cpu()
    from bcg_tpu.config import EngineConfig
    from bcg_tpu.engine.jax_engine import JaxEngine

    def cfg(**kw):
        return EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test",
            max_model_len=2048, **kw,
        )

    limit = 32 << 20
    caps = {}
    blocks = {}
    for dtype in ("int8", "int4"):
        eng = JaxEngine(cfg(kv_cache_dtype=dtype))
        eng._mem_limit = limit
        free = (eng.config.hbm_utilization * limit
                - eng._param_bytes_per_device)
        eng._prefix_budget = max(0, int(free * 0.25))
        caps[dtype] = eng.cap_for(256) or 1
        blocks[dtype] = eng._auto_pool_blocks(eng.config.kv_block_size)
        eng.shutdown()

    prompts = [
        ("honest agent system prompt", "Round 3: propose a value", DECISION),
        ("byzantine agent system prompt", "Round 3: vote now", VOTE),
    ]
    dense = JaxEngine(cfg(kv_cache_dtype="int4"))
    paged = JaxEngine(cfg(kv_cache_dtype="int4", paged_kv=True,
                          paged_kv_impl="pallas"))
    try:
        r_d = dense.batch_generate_json(prompts, temperature=0.0,
                                        max_tokens=64)
        r_p = paged.batch_generate_json(prompts, temperature=0.0,
                                        max_tokens=64)
    finally:
        dense.shutdown()
        paged.shutdown()
    mismatches = sum(1 for a, b in zip(r_d, r_p) if a != b)
    bad = sum(1 for r in r_d + r_p if not isinstance(r, dict) or "error" in r)
    if inject == "fail-rows":
        mismatches += 1  # self-test hook
    return {
        "int4.row_cap_gain": caps["int4"] / caps["int8"],
        "int4.pool_blocks_gain": blocks["int4"] / blocks["int8"],
        "int4.paged_parity_mismatches": float(mismatches),
        "int4.error_rows": float(bad),
    }


# Game-event types every completed game must carry (the manifest is
# per-file, checked separately).
_REQUIRED_GAME_EVENTS = (
    "game_start", "round_start", "decision", "deliveries", "vote",
    "round_end", "game_end",
)


def run_consensus_scenario(inject: str = "none") -> Dict[str, float]:
    """Hermetic FakeEngine consensus games with game-event telemetry on
    (BCG_TPU_GAME_EVENTS to a temp file): three seeded games — two
    fully-connected, one ring (topology-masked deliveries) — gating

    * ``convergence_rate`` / ``rounds_to_consensus_mean`` — the paper's
      outcome metrics, deterministic under the FakeEngine consensus
      policy's seeded dynamics;
    * ``event_schema_completeness`` — fraction of required event types
      present per game (manifest checked per file): a silently dropped
      emission site shows up as < 1 here, not as a mysteriously thin
      sweep report later;
    * ``events_dropped`` — the bounded sink must not shed records at
      this scale;
    * ``histogram_quantile_sanity`` — the game.round_ms registry
      histogram's bucket-derived quantiles are ordered (p50<=p95<=p99),
      non-negative, and within the declared bounds.

    ``events-off`` injection unsets the flag — the gate must then name
    the schema-completeness and convergence metrics rather than pass
    vacuously."""
    import dataclasses
    import tempfile

    from bcg_tpu.config import (
        BCGConfig, EngineConfig, GameConfig, MetricsConfig, NetworkConfig,
    )
    from bcg_tpu.obs import counters as obs_counters, game_events
    from bcg_tpu.runtime.orchestrator import BCGSimulation

    events_path = os.path.join(
        tempfile.mkdtemp(prefix="bcg-perf-gate-"), "game_events.jsonl"
    )
    # Save/restore the RAW value (None vs "") — registry accessors
    # cannot round-trip "was unset".
    prior = os.environ.get("BCG_TPU_GAME_EVENTS")  # lint: ignore[BCG-ENV-RAW]
    if inject == "events-off":
        os.environ.pop("BCG_TPU_GAME_EVENTS", None)
    else:
        os.environ["BCG_TPU_GAME_EVENTS"] = events_path
    game_events.reset_sink()
    drops_before = obs_counters.value("game.events_dropped")
    hist_before = obs_counters.value("game.round_ms.count")
    try:
        games = [
            dict(seed=7, topology="fully_connected"),
            dict(seed=8, topology="fully_connected"),
            dict(seed=3, topology="ring"),
        ]
        for spec in games:
            cfg = dataclasses.replace(
                BCGConfig(),
                game=GameConfig(num_honest=4, num_byzantine=1,
                                max_rounds=6, seed=spec["seed"]),
                network=NetworkConfig(topology_type=spec["topology"]),
                engine=EngineConfig(backend="fake"),
                metrics=MetricsConfig(save_results=False),
                verbose=False,
            )
            sim = BCGSimulation(config=cfg)
            try:
                sim.run()
            finally:
                sim.close()
        game_events.reset_sink()  # drain + close so the file is complete
    finally:
        if prior is None:
            os.environ.pop("BCG_TPU_GAME_EVENTS", None)
        else:
            os.environ["BCG_TPU_GAME_EVENTS"] = prior
        game_events.reset_sink()

    # Outcome + schema metrics come from the FILE (what a sweep would
    # actually consume), not in-process state.
    per_game: Dict[str, Dict] = {}
    have_manifest = False
    if os.path.exists(events_path):
        with open(events_path) as f:
            for line in f:
                rec = json.loads(line)
                if rec.get("event") == "manifest":
                    have_manifest = rec.get("schema_version") is not None
                    continue
                gid = rec.get("game")
                if gid is None:
                    continue
                g = per_game.setdefault(
                    gid, {"events": set(), "converged": False, "rounds": 0}
                )
                g["events"].add(rec["event"])
                if rec["event"] == "game_end":
                    g["converged"] = bool(rec.get("converged"))
                    g["rounds"] = int(rec.get("rounds", 0))
    n_games = len(per_game)
    converged = [g for g in per_game.values() if g["converged"]]
    completeness = (
        sum(
            sum(1 for e in _REQUIRED_GAME_EVENTS if e in g["events"])
            / len(_REQUIRED_GAME_EVENTS)
            for g in per_game.values()
        ) / n_games
        if n_games else 0.0
    ) * (1.0 if have_manifest or not n_games else 0.0)
    rounds_mean = (
        sum(g["rounds"] for g in converged) / len(converged)
        if converged else 0.0
    )

    try:
        hist = obs_counters.histogram("game.round_ms")  # read access
    except KeyError:
        hist = None  # recorder never ran (events-off injection)
    if hist is not None and hist.count > hist_before:
        q = hist.quantiles()
        sane = float(
            0.0 <= q["p50"] <= q["p95"] <= q["p99"] <= hist.bounds[-1]
        )
    else:
        sane = 0.0
    return {
        "consensus.convergence_rate": (
            len(converged) / n_games if n_games else 0.0
        ),
        "consensus.rounds_to_consensus_mean": rounds_mean,
        "consensus.event_schema_completeness": completeness,
        "consensus.events_dropped": float(
            obs_counters.value("game.events_dropped") - drops_before
        ),
        "consensus.histogram_quantile_sanity": sane,
    }


def run_fleet_scenario(inject: str = "none") -> Dict[str, float]:
    """Distributed observability plane (bcg_tpu/obs/fleet.py +
    scripts/fleet_report.py) on a REAL 2-process CPU cluster — the
    tests/_multihost_worker.py coordinator-handshake idiom, but each
    rank plays a FakeEngine consensus game with metric shards + game
    events on, and the last rank runs with a FROZEN fleet watermark
    (fleet.freeze_watermark, the documented chaos hook).  Gated:

    * ``shard_completeness`` — every rank's shard file present for the
      shared run id;
    * ``merged_p50_rel_err`` / ``merged_p95_rel_err`` — fleet_report's
      bucket-wise merge of the ranks' deterministic ``fleet.probe_ms``
      histograms vs a single-stream oracle bucketing the union of the
      same values in-process;
    * ``counter_merge_error`` — the merged ``fleet.probe`` counter vs
      the exact cross-rank sum the workers incremented;
    * ``events_dropped`` — the bounded event sinks shed nothing at this
      scale (summed across ranks from the merged shards);
    * ``straggler_flagged`` — the HEALTHY rank's runtime straggler pass
      (fleet.stragglers gauge in its final shard flush) flagged the
      frozen rank.  ``--inject-regression straggler-off`` disables
      detection (BCG_TPU_FLEET_STRAGGLER_FACTOR=0): the flag stays 0
      and the gate must fail naming this metric — detection can never
      pass vacuously."""
    import importlib.util
    import socket
    import subprocess
    import tempfile
    import uuid

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(root, "tests", "_fleet_worker.py")
    wspec = importlib.util.spec_from_file_location("_fleet_worker", worker)
    wmod = importlib.util.module_from_spec(wspec)
    wspec.loader.exec_module(wmod)  # formulas only; main() is guarded

    tmp = tempfile.mkdtemp(prefix="bcg-fleet-gate-")
    shard_dir = os.path.join(tmp, "shards")
    run = uuid.uuid4().hex[:12]
    nproc = 2
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord = f"127.0.0.1:{s.getsockname()[1]}"
    base_env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        PYTHONPATH=root,
        BCG_TPU_RUN_ID=run,
        BCG_TPU_METRICS_SHARD_DIR=shard_dir,
        BCG_TPU_METRICS_SHARD_MS="100",
        BCG_TPU_FLEET_STRAGGLER_FACTOR=(
            "0" if inject == "straggler-off" else "3"
        ),
    )
    procs = []
    for pid in range(nproc):
        env = dict(base_env)
        env["BCG_TPU_GAME_EVENTS"] = os.path.join(
            tmp, f"events-{pid}.jsonl"
        )
        straggle = "1" if pid == nproc - 1 else "0"
        procs.append(subprocess.Popen(
            [sys.executable, worker, coord, str(nproc), str(pid), straggle],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=root,
        ))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            raise RuntimeError(
                f"fleet worker rank {pid} failed:\n{out[-3000:]}"
            )

    fr_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "fleet_report.py"
    )
    frspec = importlib.util.spec_from_file_location("fleet_report", fr_path)
    fr = importlib.util.module_from_spec(frspec)
    frspec.loader.exec_module(fr)
    problems: List[str] = []
    records = [
        r for r in fr.load_shards([shard_dir], problems)
        if (r.get("identity") or {}).get("run_id") == run
    ]
    for problem in problems:
        print(f"perf_gate[fleet]: {problem}", file=sys.stderr)
    merged_counters = fr.merge_counters(records)
    merged_hists = fr.merge_histograms(records, problems)
    ranks = {
        (r.get("identity") or {}).get("process_index") for r in records
    }
    completeness = len(ranks) / nproc

    # Single-stream oracle: bucket the UNION of every rank's probe
    # values through one in-process registry histogram, then compare
    # fleet_report's merged quantiles against it.
    from bcg_tpu.obs.counters import Histogram

    oracle = Histogram("fleet.probe_oracle", wmod.PROBE_BOUNDS)
    for pid in range(nproc):
        for value in wmod.probe_values(pid):
            oracle.observe(value)
    oq = oracle.quantiles()
    merged_probe = merged_hists.get("fleet.probe_ms")
    if merged_probe is not None and merged_probe["count"]:
        mq = fr.histogram_quantiles(merged_probe)
        p50_err = abs(mq["p50"] - oq["p50"]) / max(1e-9, oq["p50"])
        p95_err = abs(mq["p95"] - oq["p95"]) / max(1e-9, oq["p95"])
    else:
        p50_err = p95_err = 1.0

    probe_total = merged_counters.get("fleet.probe", {}).get("total", 0)
    expected_probe = sum(100 + pid for pid in range(nproc))
    drops = (
        merged_counters.get("game.events_dropped", {}).get("total", 0)
        + merged_counters.get("serve.events_dropped", {}).get("total", 0)
    )
    flagged = 0.0
    for rec in records:
        if (rec.get("identity") or {}).get("process_index") == 0:
            flagged = float(
                (rec.get("gauges") or {}).get("fleet.stragglers", 0) >= 1
            )
    return {
        "fleet.shard_completeness": completeness,
        "fleet.merged_p50_rel_err": p50_err,
        "fleet.merged_p95_rel_err": p95_err,
        "fleet.counter_merge_error": abs(probe_total - expected_probe),
        "fleet.events_dropped": float(drops),
        "fleet.straggler_flagged": flagged,
    }


def run_hostsync_scenario(inject: str = "none") -> Dict[str, float]:
    """Runtime host-sync auditor (bcg_tpu/obs/hostsync.py) gates — the
    drift baseline for ROADMAP item 1's on-device mega-round (host-syncs
    per round -> ~1), pinned the way the while-body kernel census pinned
    PRs 8/10's fusion claims:

    * ``syncs_per_round`` — mean of the ``game.host_syncs`` per-round
      histogram over one hermetic FakeEngine consensus game run on the
      PRODUCTION round path: the fused mega-round (BCG_TPU_MEGAROUND),
      whose mirror notes exactly ONE ``round_readback`` per round —
      the fusion target reached, pinned at 1.0.
    * ``syncs_per_round_lockstep`` — the same game on the lockstep
      path: 2 batched engine calls per round (decide + vote) x 3
      mirrored decode-path syncs = 6.0.  Still pinned: every fallback
      configuration in the mega-round matrix (free-text, sequential,
      lossy channels, BPE tokenizers) runs THIS structure, so its
      drift is as load-bearing as the fused number.
    * ``syncs_per_decision`` — observed transfers per agent decision on
      the tiny REAL engine's guided-JSON benchmark (one batched call,
      3 decisions): the decode path's actual materialization count
      (prefill barrier + decode readback + step readback), exact on any
      backend.
    * ``attribution_coverage`` — attributed / total over the whole
      scenario (acceptance: >= 0.95; tracing is off here, so this is
      the jit-entry attribution path doing the work).
    * ``error_rows`` — every real-engine row parses as valid guided
      JSON (the decision benchmark can't degrade to cover a sync
      regression).

    ``hostsync-off`` injection unsets the flag — the auditor observes
    nothing and the gate must FAIL naming syncs_per_round /
    syncs_per_decision / attribution_coverage rather than pass
    vacuously (zero-surface means zero metrics, not green metrics)."""
    import dataclasses

    from bcg_tpu.config import (
        BCGConfig, EngineConfig, GameConfig, MetricsConfig,
    )
    from bcg_tpu.obs import counters as obs_counters, hostsync as obs_hostsync
    from bcg_tpu.runtime.orchestrator import BCGSimulation

    # Save/restore the RAW values (None vs "") — registry accessors
    # cannot round-trip "was unset".
    prior = os.environ.get("BCG_TPU_HOSTSYNC")  # lint: ignore[BCG-ENV-RAW]
    prior_mega = os.environ.get("BCG_TPU_MEGAROUND")  # lint: ignore[BCG-ENV-RAW]
    if inject == "hostsync-off":
        os.environ.pop("BCG_TPU_HOSTSYNC", None)
    else:
        os.environ["BCG_TPU_HOSTSYNC"] = "1"
    obs_hostsync.reset()
    total_before = obs_counters.value("engine.hostsync.total")
    attr_before = obs_counters.value("engine.hostsync.attributed")
    try:
        # Arm 1: hermetic FakeEngine game (same geometry as the
        # consensus scenario's converging seed), once per round path —
        # fused mega-round (the production profile, 1 readback/round)
        # and lockstep (the fallback-matrix profile, 2 calls x 3 syncs).
        cfg = dataclasses.replace(
            BCGConfig(),
            game=GameConfig(num_honest=4, num_byzantine=1,
                            max_rounds=6, seed=7),
            engine=EngineConfig(backend="fake"),
            metrics=MetricsConfig(save_results=False),
            verbose=False,
        )
        per_round = {}
        for path_name, mega in (("fused", "1"), ("lockstep", None)):
            if mega is None:
                os.environ.pop("BCG_TPU_MEGAROUND", None)
            else:
                os.environ["BCG_TPU_MEGAROUND"] = mega
            rounds_before = obs_counters.value("game.host_syncs.count")
            round_syncs_before = obs_counters.value("game.host_syncs.sum")
            sim = BCGSimulation(config=cfg)
            try:
                sim.run()
            finally:
                sim.close()
            rounds = (
                obs_counters.value("game.host_syncs.count") - rounds_before
            )
            round_syncs = (
                obs_counters.value("game.host_syncs.sum")
                - round_syncs_before
            )
            per_round[path_name] = round_syncs / rounds if rounds else 0.0

        # Arm 2: tiny real engine, guided-JSON decision benchmark
        # (deterministic at temperature 0 — the engine scenario's
        # prompt set).
        _force_cpu()
        from bcg_tpu.engine.jax_engine import JaxEngine

        prompts = [
            ("honest agent system prompt", "Round 3: propose a value",
             DECISION),
            ("byzantine agent system prompt", "Round 3: vote now", VOTE),
            ("honest agent system prompt", "Round 4: propose a value",
             DECISION),
        ]
        eng_before = obs_counters.value("engine.hostsync.total")
        eng = JaxEngine(EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test",
            max_model_len=2048,
        ))
        try:
            results = eng.batch_generate_json(
                prompts, temperature=0.0, max_tokens=64
            )
        finally:
            eng.shutdown()
        decision_syncs = (
            obs_counters.value("engine.hostsync.total") - eng_before
        )
        bad = sum(
            1 for r in results if not isinstance(r, dict) or "error" in r
        )
        total = obs_counters.value("engine.hostsync.total") - total_before
        attributed = (
            obs_counters.value("engine.hostsync.attributed") - attr_before
        )
    finally:
        if prior is None:
            os.environ.pop("BCG_TPU_HOSTSYNC", None)
        else:
            os.environ["BCG_TPU_HOSTSYNC"] = prior
        if prior_mega is None:
            os.environ.pop("BCG_TPU_MEGAROUND", None)
        else:
            os.environ["BCG_TPU_MEGAROUND"] = prior_mega
        obs_hostsync.reset()
    return {
        "hostsync.syncs_per_round": per_round.get("fused", 0.0),
        "hostsync.syncs_per_round_lockstep": per_round.get("lockstep", 0.0),
        "hostsync.syncs_per_decision": decision_syncs / len(prompts),
        "hostsync.attribution_coverage": (
            attributed / total if total else 0.0
        ),
        "hostsync.error_rows": float(bad),
    }


def run_megaround_scenario(inject: str = "none") -> Dict[str, float]:
    """Fused mega-round gates (bcg_tpu/engine/megaround.py) — ROADMAP
    item 1's decision-identity + retrace-pinning + throughput claims,
    on the tiny real engine:

    * ``decision_mismatches`` / ``vote_mismatches`` — greedy decision
      identity vs the lockstep oracle (max 0 EXACT): each fused round's
      proposals and votes must equal what ``batch_generate_json`` at
      temperature 0 produces over the SAME rendered template prompts
      with the SAME token budget.  The fused path shares the decode-loop
      body (``_decode_loop_fn``) with the lockstep jit, so any
      divergence is an assembly/parse bug, not sampler drift.
    * ``steady_retraces`` — compile + retrace counter movement on the
      ``megaround`` entry across rounds 2..R (must be 0 EXACT): values,
      inbox, round number, and convergence state are traced arguments,
      so steady-state rounds reuse ONE compiled program.
    * ``round_speedup`` — warm fused-round wall-clock vs the warm
      lockstep pair (decide + vote ``batch_generate_json`` over the
      same prompts, measured in THIS process on the same warm engine).
      Banded min > 1: the fusion must beat the path it replaces or the
      claim is noise.
    """
    import time

    import numpy as np

    _force_cpu()
    from bcg_tpu.config import EngineConfig
    from bcg_tpu.engine.jax_engine import JaxEngine
    from bcg_tpu.obs import counters as obs_counters

    n, lo, hi, max_rounds = 4, 0, 50, 6
    eng = JaxEngine(EngineConfig(
        backend="jax", model_name="bcg-tpu/tiny-test", max_model_len=2048,
    ))
    try:
        plan = eng.prepare_megaround(
            n_agents=n, lo=lo, hi=hi, max_rounds=max_rounds
        )
        template = plan.template
        mask = np.ones((n, n), dtype=bool)
        np.fill_diagonal(mask, False)
        is_byz = np.zeros(n, dtype=bool)
        is_byz[-1] = True
        values = np.array([3, 17, 3, 42], dtype=np.int32)
        initials = values.copy()
        inbox = np.full((n, n), -1, dtype=np.int32)

        def parse(row, lo_, hi_):
            if not isinstance(row, dict) or "error" in row:
                return -1
            v = row.get("value")
            if isinstance(v, bool) or not isinstance(v, int):
                return -1
            return v if lo_ <= v <= hi_ else -1

        decision_mismatches = vote_mismatches = 0
        fused_warm = oracle_warm = 0.0
        compile_after_first = retrace_after_first = 0.0
        for r in range(1, 4):
            t0 = time.perf_counter()
            res = eng.run_megaround(
                plan, values, inbox, r, mask, is_byz, initials
            )
            t_fused = time.perf_counter() - t0
            # Lockstep oracle: the SAME rendered prompts through the
            # ordinary batched guided path at temperature 0, with each
            # phase's exact fused token budget (so guaranteed-parse
            # masking binds identically).
            t0 = time.perf_counter()
            oracle_dec = eng.batch_generate_json(
                template.decision_prompts(values, inbox, r),
                temperature=0.0, max_tokens=plan.decide.max_new,
            )
            oracle_vote = eng.batch_generate_json(
                template.vote_prompts(res.values, res.received, r),
                temperature=0.0, max_tokens=plan.vote.max_new,
            )
            t_oracle = time.perf_counter() - t0
            want_dec = [parse(row, lo, hi) for row in oracle_dec]
            want_vote = [
                1 if parse(row, 0, 1) == 1 else 0 for row in oracle_vote
            ]
            decision_mismatches += int(
                (np.asarray(want_dec, dtype=np.int32) != res.proposed).sum()
            )
            vote_mismatches += int(
                (np.asarray(want_vote, dtype=np.int32) != res.votes).sum()
            )
            if r == 1:
                compile_after_first = obs_counters.value(
                    "engine.compile.megaround"
                )
                retrace_after_first = obs_counters.value(
                    "engine.retrace.megaround"
                )
            else:
                # Rounds 2+ are warm on both paths (round 1 paid every
                # compile): the throughput comparison.
                fused_warm += t_fused
                oracle_warm += t_oracle
            values, inbox = res.values, res.received
        steady = (
            obs_counters.value("engine.compile.megaround")
            - compile_after_first
        ) + (
            obs_counters.value("engine.retrace.megaround")
            - retrace_after_first
        )
    finally:
        eng.shutdown()
    return {
        "megaround.decision_mismatches": float(decision_mismatches),
        "megaround.vote_mismatches": float(vote_mismatches),
        "megaround.steady_retraces": float(steady),
        "megaround.round_speedup": (
            oracle_warm / fused_warm if fused_warm > 0 else 0.0
        ),
    }


def run_compile_scenario(inject: str = "none") -> Dict[str, float]:
    """Compile-cost observability (bcg_tpu/obs/compile.py) gates — the
    drift baseline for ROADMAP item 1's mega-round and the sweep tier's
    per-tenant signature multiplication, pinned the way hostsync pinned
    the transfer structure:

    * ``steady_state_retraces`` — compile + retrace counter movement
      over an identical-shape warm repeat call (must be 0 EXACT: the
      observer's seams are the SAME trace-cache-miss accounting the
      engine already keys on, so enabling observability can never
      provoke a compile).
    * ``retrace_cause_coverage`` — structured cause records emitted per
      counted retrace over a PROVOKED retrace (a new max_tokens on the
      warm engine ⇒ new max_new/cache_len signatures).  Acceptance:
      every counted retrace carries a cause (min 0.95).
    * ``compile_cache_entries`` — distinct (entry, signature) pairs the
      observer accounted over the whole scenario (banded: the tiny
      engine's prefill + decode_loop signatures, cold + provoked).
    * ``error_rows`` — every row parses as valid guided JSON (the
      decision benchmark can't degrade to cover a compile regression).

    ``compile-off`` injection unsets the flag — the observer accounts
    nothing and the gate must FAIL naming retrace_cause_coverage /
    compile_cache_entries rather than pass vacuously (zero-surface
    means zero metrics, not green metrics)."""
    _force_cpu()
    from bcg_tpu.config import EngineConfig
    from bcg_tpu.engine.jax_engine import JaxEngine
    from bcg_tpu.obs import compile as obs_compile
    from bcg_tpu.obs import counters as obs_counters

    # Save/restore the RAW value (None vs "") — registry accessors
    # cannot round-trip "was unset".
    prior = os.environ.get("BCG_TPU_COMPILE_OBS")  # lint: ignore[BCG-ENV-RAW]
    if inject == "compile-off":
        os.environ.pop("BCG_TPU_COMPILE_OBS", None)
    else:
        os.environ["BCG_TPU_COMPILE_OBS"] = "1"
    obs_compile.reset()
    prompts = [
        ("honest agent system prompt", "Round 3: propose a value", DECISION),
        ("byzantine agent system prompt", "Round 3: vote now", VOTE),
        ("honest agent system prompt", "Round 4: propose a value", DECISION),
    ]
    try:
        eng = JaxEngine(EngineConfig(
            backend="jax", model_name="bcg-tpu/tiny-test",
            max_model_len=2048,
        ))
        try:
            cold = eng.batch_generate_json(prompts, temperature=0.0,
                                           max_tokens=64)
            # Steady state: an identical-shape repeat compiles NOTHING.
            before_warm = obs_counters.snapshot()
            warm = eng.batch_generate_json(prompts, temperature=0.0,
                                           max_tokens=64)
            warm_moved = obs_counters.delta(before_warm)
            # Provoked retrace: a new token budget on the warm engine is
            # a new max_new (decode loop) and cache_len (prefill)
            # signature — each must carry exactly one cause record.
            before_provoke = obs_counters.snapshot()
            provoked = eng.batch_generate_json(prompts, temperature=0.0,
                                               max_tokens=96)
            provoke_moved = obs_counters.delta(before_provoke)
        finally:
            eng.shutdown()
        # Per-scenario population from THE OBSERVER OBJECT, not a gauge
        # delta: the gauge holds absolute values, and an observer an
        # earlier in-process scenario created (any note_signature under
        # BCG_TPU_COMPILE_OBS) may have left it higher than this fresh
        # observer's count — a delta would go negative and fail the
        # band spuriously.  compile-off: no observer, 0.
        obs_active = obs_compile.observer()
        entries = (
            obs_active.brief()["cache_entries"]
            if obs_active is not None else 0
        )
    finally:
        if prior is None:
            os.environ.pop("BCG_TPU_COMPILE_OBS", None)
        else:
            os.environ["BCG_TPU_COMPILE_OBS"] = prior
        obs_compile.reset()
    # Prefix note: the observer's own families spell their segment with
    # an underscore (engine.compile_ms / engine.compile_obs /
    # engine.retrace_cause), so the dotted engine.compile. /
    # engine.retrace. prefixes below match ONLY the per-entry
    # trace-cache counters.
    steady = sum(
        v for k, v in warm_moved.items()
        if k.startswith(("engine.retrace.", "engine.compile."))
    )
    retraces = sum(
        v for k, v in provoke_moved.items()
        if k.startswith("engine.retrace.")
    )
    causes = sum(
        v for k, v in provoke_moved.items()
        if k.startswith("engine.retrace_cause.")
    )
    bad = sum(
        1 for r in cold + warm + provoked
        if not isinstance(r, dict) or "error" in r
    )
    return {
        "compile.steady_state_retraces": float(steady),
        "compile.retrace_cause_coverage": (
            causes / retraces if retraces else 0.0
        ),
        "compile.compile_cache_entries": float(entries),
        "compile.error_rows": float(bad),
    }


def run_sweep_scenario(inject: str = "none") -> Dict[str, float]:
    """Multi-tenant scheduling gates (the sweep tier's games-as-tenants
    contract, bcg_tpu/sweep + serve/scheduler.py tenancy), all
    deterministic: the device is PLUGGED (run_exclusive holds the
    device lock) while requests queue, so batch formation order is a
    pure function of the queue content.

    * ``starvation_ratio`` — 2 tenants through a FakeEngine scheduler
      (bucket 8 rows, linger 0): "heavy" floods 16 x 4-row requests,
      "light" submits 2.  The metric is the mean normalized batch
      position of the light tenant's rows: weighted-fair selection
      rides them in the FIRST post-plug batch (~0.1); FIFO drowns them
      behind the heavy backlog (~1.0).  ``--inject-regression
      fairness-off`` (Scheduler(fair=False)) must fail naming this
      metric.
    * ``fairness_batches`` — dispatch-count floor so the ratio can
      never pass vacuously on a degenerate single-batch run.
    * ``quota_overrun_rows`` / ``quota_deferrals`` — a tenant with an
      8-row quota: its queued-row high-water can NEVER exceed the quota
      (exactness, 0 exact) and the over-quota submit defers (>= 1)
      with a positive retry-after (``retry_after_live_ms``).
    * ``retry_after_monotonicity`` — the retry-after derivation
      (derive_retry_after_ms) over a headroom grid at a fixed SLO:
      1.0 iff non-increasing in headroom AND the zero-headroom backoff
      is >= 2x the full-headroom base (the serve.slo.headroom_ms
      histogram actually steers admission, monotonically).
    * ``error_rows`` — every scheduled row parses as valid guided JSON.
    """
    from bcg_tpu.engine.fake import FakeEngine
    from bcg_tpu.serve.scheduler import (
        AdmissionDeferred, Scheduler, derive_retry_after_ms,
    )

    class RecordingEngine:
        """FakeEngine proxy: records each dispatched batch's row
        markers (the first character of every user prompt) and adds a
        small device latency so dispatches are distinct batches."""

        def __init__(self):
            self.inner = FakeEngine(seed=0, policy="consensus")
            self.batches: List[List[str]] = []

        def batch_generate_json(self, prompts, temperature=0.8,
                                max_tokens=512):
            self.batches.append([p[1][0] for p in prompts])
            import time as _time

            _time.sleep(0.002)
            return self.inner.batch_generate_json(
                prompts, temperature=temperature, max_tokens=max_tokens
            )

    def _plug(sched):
        """Hold the device lock until released — dispatches form but
        cannot run, so queued work accumulates deterministically."""
        release = threading.Event()
        plugged = threading.Event()

        def hold():
            plugged.set()
            release.wait()

        t = threading.Thread(target=lambda: sched.run_exclusive(hold))
        t.start()
        plugged.wait(10)
        return release, t

    def _row(marker: str):
        return ("agent system prompt",
                f"{marker} Round 2. agent_1 value: 17. Your current "
                "value: 17. Decide.", DECISION)

    def _drain_queue(sched, deadline_s: float = 10.0) -> None:
        import time as _time

        t0 = _time.monotonic()
        poll_s = 0.0005
        while sched.queue_depth_rows() > 0:
            if _time.monotonic() - t0 > deadline_s:
                raise RuntimeError("scheduler never picked up the seed batch")
            _time.sleep(poll_s)  # backoff, not fixed-cadence (BCG-RETRY-SLEEP)
            poll_s = min(poll_s * 2, 0.01)

    # --- fairness arm -------------------------------------------------
    eng = RecordingEngine()
    sched = Scheduler(
        eng, linger_ms=0, bucket_rows=8, max_queue_rows=4096,
        deadline_ms=0, strict_admission=False,
        fair=(inject != "fairness-off"),
    )
    sched.register_tenant("heavy", weight=1.0)
    sched.register_tenant("light", weight=1.0)
    release, plug_thread = _plug(sched)
    try:
        reqs = [sched.submit(("json",), [_row("H")] * 4, [0.0] * 4,
                             [64] * 4, tenant="heavy")]
        _drain_queue(sched)  # seed batch in flight, blocked on the plug
        for _ in range(15):
            reqs.append(sched.submit(("json",), [_row("H")] * 4,
                                     [0.0] * 4, [64] * 4, tenant="heavy"))
        for _ in range(2):
            reqs.append(sched.submit(("json",), [_row("L")] * 4,
                                     [0.0] * 4, [64] * 4, tenant="light"))
    finally:
        release.set()
        plug_thread.join(10)
    for r in reqs:
        r.done.wait(30)
    sched.close()
    bad = sum(
        1 for r in reqs for row in (r.results or [])
        if not isinstance(row, dict) or "error" in row
    )
    n_batches = len(eng.batches)
    light_idx = [i for i, b in enumerate(eng.batches) if "L" in b]
    starvation = (
        sum(light_idx) / len(light_idx) / max(1, n_batches - 1)
        if light_idx else 1.0
    )

    # --- quota arm ----------------------------------------------------
    eng2 = FakeEngine(seed=0, policy="consensus")
    sched2 = Scheduler(eng2, linger_ms=0, max_queue_rows=4096,
                       deadline_ms=0, strict_admission=False)
    q = sched2.register_tenant("quotatenant", quota_rows=8)
    release2, plug2 = _plug(sched2)
    retry_ms = 0.0
    try:
        first = sched2.submit(("json",), [_row("Q")] * 4, [0.0] * 4,
                              [64] * 4, tenant="quotatenant")
        _drain_queue(sched2)
        fills = [sched2.submit(("json",), [_row("Q")] * 4, [0.0] * 4,
                               [64] * 4, tenant="quotatenant")
                 for _ in range(2)]
        over = sched2.submit(("json",), [_row("Q")] * 4, [0.0] * 4,
                             [64] * 4, tenant="quotatenant")
        if isinstance(over.error, AdmissionDeferred):
            retry_ms = over.error.retry_after_s * 1e3
    finally:
        release2.set()
        plug2.join(10)
    for r in [first] + fills:
        r.done.wait(30)
    sched2.close()
    overrun = max(0, q.max_queued_rows - 8)

    # --- retry-after shape (pure) ------------------------------------
    slo = 50
    grid = [derive_retry_after_ms(20.0, 10.0, slo_ms=slo,
                                  headroom_p50_ms=float(h))
            for h in range(0, slo + 1, 5)]
    monotone = all(a >= b for a, b in zip(grid, grid[1:]))
    responsive = grid[0] >= 2.0 * grid[-1]
    return {
        "sweep.starvation_ratio": starvation,
        "sweep.fairness_batches": float(n_batches),
        "sweep.quota_overrun_rows": float(overrun),
        "sweep.quota_deferrals": float(q.deferrals),
        "sweep.retry_after_live_ms": retry_ms,
        "sweep.retry_after_monotonicity": float(monotone and responsive),
        "sweep.error_rows": float(bad),
    }


def run_chaos_scenario(inject: str = "none") -> Dict[str, float]:
    """Chaos seam injection + recovery tier gates (runtime/resilience.py
    + the serve dispatch retry/supervisor ladder + the sweep job-requeue
    policy), all hermetic and deterministic — the scheduler's single
    dispatch thread makes seam occurrences strictly sequential, so an
    occurrence-indexed chaos spec fires the same faults at the same
    passes on every run:

    * serve arm — a seeded FakeEngine serving run (2 waves x 8 threads
      x 2-row guided requests, 4-row bucket, retries=2, watchdog 1.5s +
      engine_factory) under an injected engine CRASH (dispatch pass 2),
      device-call HANG (pass 4, 4s > watchdog), and PoolExhausted
      (pass 6).  Every fault must recover: completed_fraction 1.0,
      lost_futures/failed_requests/error_rows 0, and the recovery
      counters (dispatch_retries, recoveries, engine_rebuilds,
      batch_splits) land EXACTLY where the spec puts them — plus the
      serve.recovery_ms histogram's quantile sanity.
    * sweep arm — a 3-job FakeEngine sweep with a transient job crash
      injected at job pass 2 and a retry budget: the job must requeue,
      complete, and report exactly once (sweep_jobs_retried >= 1,
      completed_fraction 1.0, duplicate-job problems EMPTY via the real
      consensus_report parser).

    ``chaos-off`` injection unsets BCG_TPU_CHAOS: nothing fires, nothing
    recovers, and the gate must FAIL naming the retry/recovery/rebuild
    metrics rather than pass vacuously (zero faults means zero recovery
    evidence, not green recovery)."""
    import importlib.util
    import tempfile

    from bcg_tpu.engine.fake import FakeEngine
    from bcg_tpu.obs import counters as obs_counters
    from bcg_tpu.runtime import resilience
    from bcg_tpu.serve.scheduler import Scheduler
    from bcg_tpu.sweep.controller import run_sweep

    chaos_on = inject != "chaos-off"
    # Save/restore the RAW value (None vs "") — registry accessors
    # cannot round-trip "was unset".
    prior = os.environ.get("BCG_TPU_CHAOS")  # lint: ignore[BCG-ENV-RAW]
    before = obs_counters.snapshot()

    # --- serve arm: crash + hang + exhaust, all recovered -------------
    if chaos_on:
        os.environ["BCG_TPU_CHAOS"] = (
            "seed=7;crash@serve.dispatch:2;hang@serve.dispatch:4:4.0;"
            "exhaust@serve.dispatch:6"
        )
    else:
        os.environ.pop("BCG_TPU_CHAOS", None)
    resilience.reset()
    try:
        sched = Scheduler(
            FakeEngine(seed=0, policy="consensus"),
            linger_ms=0, bucket_rows=4, max_queue_rows=4096, deadline_ms=0,
            strict_admission=False, max_dispatch_retries=2,
            watchdog_s=1.5,
            engine_factory=lambda: FakeEngine(seed=0, policy="consensus"),
        )
        payload = [
            ("agent system prompt",
             "Round 2. agent_1 value: 17. agent_2 value: 17. "
             "Your current value: 17. Decide.",
             DECISION),
        ] * 2
        errors: List[BaseException] = []
        row_counts = {"rows": 0, "error_rows": 0}
        count_lock = threading.Lock()

        def one_request():
            try:
                out = sched.submit_and_wait(
                    ("json",), list(payload), [0.0] * 2, [64] * 2
                )
                bad = sum(
                    1 for r in out if not isinstance(r, dict) or "error" in r
                )
                with count_lock:
                    row_counts["rows"] += len(out)
                    row_counts["error_rows"] += bad
            except BaseException as e:  # lost futures surface as metrics
                errors.append(e)

        for _wave in range(2):
            threads = [
                threading.Thread(target=one_request) for _ in range(8)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        snap = sched.snapshot()
        sched.close()

        # --- sweep arm: transient job crash, requeued, reported once --
        if chaos_on:
            os.environ["BCG_TPU_CHAOS"] = "seed=7;crash@sweep.job:2"
        resilience.reset()
        sweep_dir = os.path.join(
            tempfile.mkdtemp(prefix="bcg-chaos-gate-"), "sweep"
        )
        spec = {
            "name": "chaos-sweep",
            "base": {"agents": 3, "byzantine": 0, "max_rounds": 3,
                     "backend": "fake"},
            "axes": {"seed": [1, 2, 3]},
        }
        summary = run_sweep(
            spec, sweep_dir, max_concurrent=1,
            engine=FakeEngine(seed=0, policy="consensus"),
            max_job_retries=2,
        )
    finally:
        if prior is None:
            os.environ.pop("BCG_TPU_CHAOS", None)
        else:
            os.environ["BCG_TPU_CHAOS"] = prior
        resilience.reset()
    moved = obs_counters.delta(before)

    # Duplicate-job detection over the sweep's event files, through the
    # REAL merge consumer (scripts/consensus_report.py) — a requeued job
    # must never double its game_end.
    cr_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "consensus_report.py"
    )
    cr_spec = importlib.util.spec_from_file_location("consensus_report", cr_path)
    cr = importlib.util.module_from_spec(cr_spec)
    cr_spec.loader.exec_module(cr)
    import glob as _glob

    games, problems = [], []
    for path in sorted(_glob.glob(os.path.join(sweep_dir, "events-*.jsonl"))):
        games.extend(cr.parse_file(path, problems))
    dup_problems = cr.duplicate_job_problems(games)

    # serve.recovery_ms quantile sanity (the structural histogram gate —
    # wall-clock quantile VALUES are not banded, ordering is).  The
    # count guard reads the SCENARIO's movement, not the process
    # absolute: an earlier in-process recovery (another test) must not
    # let the chaos-off arm pass this vacuously.
    try:
        hist = obs_counters.histogram("serve.recovery_ms")
        q = hist.quantiles()
        hist_sane = float(
            moved.get("serve.recovery_ms.count", 0) > 0
            and 0.0 <= q["p50"] <= q["p95"] <= q["p99"] <= hist.bounds[-1]
        )
    except KeyError:
        hist_sane = 0.0
    if errors:
        raise errors[0]
    return {
        "chaos.completed_fraction": (
            snap["completed"] / max(1, snap["submitted"])
        ),
        "chaos.lost_futures": float(snap["pending"]),
        "chaos.failed_requests": float(snap["failed"]),
        "chaos.error_rows": float(row_counts["error_rows"]),
        "chaos.dispatch_retries": moved.get("serve.dispatch_retries", 0),
        "chaos.batch_splits": moved.get("serve.batch_splits", 0),
        "chaos.recoveries": moved.get("serve.recoveries", 0),
        "chaos.engine_rebuilds": moved.get("serve.engine_rebuilds", 0),
        "chaos.faults_injected": moved.get("chaos.injected", 0),
        "chaos.recovery_hist_sanity": hist_sane,
        "chaos.sweep_completed_fraction": (
            summary["completed"] / max(1, len(summary["results"]))
        ),
        "chaos.sweep_jobs_retried": moved.get("sweep.jobs.retried", 0),
        "chaos.sweep_duplicate_job_problems": float(len(dup_problems)),
    }


def run_scenarios_scenario(inject: str = "none") -> Dict[str, float]:
    """Adversary library + scenario registry gates (bcg_tpu/scenarios):
    a 4-scenario FakeEngine sweep (adaptive-margin, baseline-disrupt,
    clique-collusion, equivocation-split at seed 0) through the REAL
    sweep controller — each job derives its role-aware scripted policy
    from the registry (no injected engine) — consumed by the REAL
    report parser (scripts/consensus_report.py):

    * ``influence_<strategy>`` — per-strategy byzantine_influence
      floors (non-vacuity: every scripted adversary must actually move
      honest values, not just exist in config);
    * ``equivocation_divergence_rows`` — (round, sender) pairs whose
      delivered values differ across receivers, floored >= 1 under the
      equivocating strategy; ``offstrategy_divergence_rows`` pinned 0
      EXACT (only the equivocator may split values per receiver);
    * ``clique_shared_target_agreement`` — fraction of byzantine
      decisions in the clique games equal to the seed-derived
      ``clique_target`` (1.0 exact: collusion is scripted arithmetic);
    * ``strategies_covered`` — distinct strategies stamped in
      game_start (4 exact); ``error_rows`` — invalid decisions (0).

    ``scenarios-off`` injection runs the same grid shape with the
    registry unplugged (plain default jobs, no scenario key): the
    influence floors, coverage, divergence, and clique agreement must
    all FAIL loudly rather than pass vacuously."""
    import glob as _glob
    import importlib.util
    import tempfile

    from bcg_tpu.scenarios.strategies import clique_target
    from bcg_tpu.sweep.controller import run_sweep

    scen = ["adaptive-margin", "baseline-disrupt", "clique-collusion",
            "equivocation-split"]
    if inject == "scenarios-off":
        spec = {"name": "scenarios-gate",
                "base": {"agents": 6, "byzantine": 2, "max_rounds": 6},
                "axes": {"seed": [0, 1, 2, 3]}}
    else:
        spec = {"name": "scenarios-gate", "axes": {"scenario": scen}}
    out_dir = os.path.join(
        tempfile.mkdtemp(prefix="bcg-scen-gate-"), "sweep"
    )
    run_sweep(spec, out_dir, max_concurrent=1, max_job_retries=2)

    cr_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "consensus_report.py"
    )
    cr_spec = importlib.util.spec_from_file_location(
        "consensus_report", cr_path
    )
    cr = importlib.util.module_from_spec(cr_spec)
    cr_spec.loader.exec_module(cr)
    games: List = []
    problems: List[str] = []
    event_files = sorted(
        _glob.glob(os.path.join(out_dir, "events-*.jsonl"))
    )
    for path in event_files:
        games.extend(cr.parse_file(path, problems))

    influence: Dict[str, int] = {}
    equiv_rows = off_rows = invalids = 0
    strategies = set()
    for g in games:
        if not g.ended:
            continue
        invalids += g.invalids
        if g.strategy:
            strategies.add(g.strategy)
            influence[g.strategy] = (
                influence.get(g.strategy, 0) + g.influence
            )
            if g.strategy == "equivocate":
                equiv_rows += g.equivocation_rows
            else:
                off_rows += g.equivocation_rows

    # Clique oracle: collusion is pure arithmetic, so EVERY byzantine
    # decision in the clique games must equal the seed-derived target —
    # read straight from the decision events, not the aggregates.
    clique_hits = clique_total = 0
    for path in event_files:
        meta: Dict[str, Dict] = {}
        with open(path) as f:
            for line in f:
                rec = json.loads(line)
                if rec.get("event") == "game_start":
                    meta[rec["game"]] = rec
                elif (rec.get("event") == "decision"
                      and rec.get("role") == "byzantine"
                      and rec.get("value") is not None):
                    start = meta.get(rec.get("game"))
                    if start and start.get("strategy") == "clique":
                        lo_, hi_ = start["value_range"]
                        clique_total += 1
                        clique_hits += int(
                            rec["value"]
                            == clique_target(start.get("seed"), lo_, hi_)
                        )
    return {
        "scenarios.influence_disrupt": float(influence.get("disrupt", 0)),
        "scenarios.influence_clique": float(influence.get("clique", 0)),
        "scenarios.influence_adaptive": float(
            influence.get("adaptive", 0)
        ),
        "scenarios.influence_equivocate": float(
            influence.get("equivocate", 0)
        ),
        "scenarios.equivocation_divergence_rows": float(equiv_rows),
        "scenarios.offstrategy_divergence_rows": float(off_rows),
        "scenarios.clique_shared_target_agreement": (
            clique_hits / clique_total if clique_total else 0.0
        ),
        "scenarios.strategies_covered": float(len(strategies)),
        "scenarios.error_rows": float(invalids),
    }


def run_hlo_scenario(inject: str = "none") -> Dict[str, float]:
    """Kernel-census drift findings (scripts/hlo_census.py) as a gated
    metric — 0 findings = the lowered programs still match
    hlo_baseline.json.

    Runs as a SUBPROCESS, not in-process: XLA's fusion decisions depend
    on the host-platform device count, which is frozen at first jax
    import — a gate process that already ran other scenarios could not
    adopt the 8-device virtual-mesh geometry the census script (and
    tests/conftest.py) pin, and would diff against the baseline with
    the wrong lowering."""
    import subprocess

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "hlo_census.py")
    proc = subprocess.run(
        [sys.executable, path, "--check"],
        capture_output=True, text=True, timeout=580,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    findings = [line for line in proc.stderr.splitlines()
                if line.startswith("DRIFT: ")]
    if proc.returncode not in (0, 2):  # crash, not a drift verdict
        findings.append(
            f"census subprocess failed rc={proc.returncode}: "
            + proc.stderr.strip()[-300:]
        )
    for f in findings:
        print(f"perf_gate[hlo]: {f}", file=sys.stderr)
    return {"hlo.census_drift_findings": float(len(findings))}


def run_alerts_scenario(inject: str = "none") -> Dict[str, float]:
    """Health & alerting plane gates (bcg_tpu/obs/alerts.py) driven
    over the chaos scenario's serve recipe — the evaluator watches a
    run the gate KNOWS contains exactly 3 faults (crash at dispatch
    pass 2, 4s hang at pass 4, PoolExhausted at pass 6), with the
    periodic thread parked (BCG_TPU_ALERT_MS=1h) so every evaluation
    cycle is driven explicitly and firing windows are deterministic:

    * oracle arm — a fault-free FakeEngine serving run under manual
      evaluation cycles: ``false_positives`` 0 EXACT (a quiet healthy
      process may not alert; threshold rules read ABSOLUTE gauges, so
      this arm starts from a reset registry — see SCENARIOS comment).
    * chaos arm — the crash+hang+exhaust run with one evaluation cycle
      per wave: the expected recovery rules (engine_errors,
      engine_rebuilt, dispatch_retries) each fire exactly once
      (``chaos_alerts_fired`` floored at the injected-fault count,
      ``fault_coverage`` >= 1), every episode resolves on the
      post-close quiet cycles (``unresolved_at_end`` 0, ``flaps`` 0 —
      a condition spanning consecutive cycles is ONE episode), no
      unexpected rule fires, ``health()`` flips failing while the
      engine_errors page alert is up and back (``healthz_flip`` 1),
      readiness flips unready INSIDE the hang window and back — read
      from the pushed transition history, no polling race
      (``readyz_flip`` 1) — and the JSONL alert stream's record counts
      match the engine's fired/resolved totals (``event_stream_ok``).

    ``alerts-off`` injection unsets BCG_TPU_ALERTS: the same faulted
    run evaluates NOTHING and the gate must FAIL naming
    rules_evaluated / chaos_alerts_fired / fault_coverage /
    healthz_flip / event_stream_ok rather than pass vacuously (zero
    observed faults means zero alerting evidence, not green alerting).
    readyz_flip stays 1 by DESIGN: readiness is plain module state the
    scheduler pushes regardless of the alerting flag."""
    import tempfile

    from bcg_tpu.engine.fake import FakeEngine
    from bcg_tpu.obs import alerts as obs_alerts
    from bcg_tpu.obs import counters as obs_counters
    from bcg_tpu.runtime import resilience
    from bcg_tpu.serve.scheduler import Scheduler

    alerts_on = inject != "alerts-off"
    # Save/restore the RAW values (None vs "") — registry accessors
    # cannot round-trip "was unset".
    prior_alerts = os.environ.get("BCG_TPU_ALERTS")  # lint: ignore[BCG-ENV-RAW]
    prior_ms = os.environ.get("BCG_TPU_ALERT_MS")  # lint: ignore[BCG-ENV-RAW]
    prior_events = os.environ.get("BCG_TPU_ALERT_EVENTS")  # lint: ignore[BCG-ENV-RAW]
    prior_chaos = os.environ.get("BCG_TPU_CHAOS")  # lint: ignore[BCG-ENV-RAW]

    events_path = os.path.join(
        tempfile.mkdtemp(prefix="bcg-alert-gate-"), "alerts.jsonl"
    )
    if alerts_on:
        os.environ["BCG_TPU_ALERTS"] = "1"
    else:
        os.environ.pop("BCG_TPU_ALERTS", None)
    os.environ["BCG_TPU_ALERT_MS"] = "3600000"
    os.environ["BCG_TPU_ALERT_EVENTS"] = events_path
    # Threshold/staleness rules read absolute registry values; earlier
    # scenarios legitimately leave stale heartbeats / zero headroom /
    # straggler verdicts behind.  The 0-exact false-positive pin needs
    # a pristine registry ('alerts' runs last for this reason).
    obs_counters.reset()
    obs_alerts.reset()
    obs_alerts.reset_readiness()
    resilience.reset()

    payload = [
        ("agent system prompt",
         "Round 2. agent_1 value: 17. agent_2 value: 17. "
         "Your current value: 17. Decide.",
         DECISION),
    ] * 2
    expected = ("engine_errors", "engine_rebuilt", "dispatch_retries")
    saw_failing = False
    final_ok = False
    try:
        # --- oracle arm: healthy traffic may not alert ----------------
        os.environ.pop("BCG_TPU_CHAOS", None)
        sched = Scheduler(
            FakeEngine(seed=0, policy="consensus"),
            linger_ms=0, bucket_rows=4, max_queue_rows=4096,
            deadline_ms=0, strict_admission=False,
        )
        obs_alerts.evaluate_now()  # base snapshot: rate rules need two
        for _ in range(2):
            sched.submit_and_wait(
                ("json",), list(payload), [0.0] * 2, [64] * 2
            )
            obs_alerts.evaluate_now()
        sched.close()
        obs_alerts.evaluate_now()
        eng = obs_alerts.engine()
        false_pos = float(eng.fired) if eng is not None else 0.0

        # --- chaos arm: the PR-15 recipe, one cycle per wave ----------
        before = obs_counters.snapshot()
        os.environ["BCG_TPU_CHAOS"] = (
            "seed=7;crash@serve.dispatch:2;hang@serve.dispatch:4:4.0;"
            "exhaust@serve.dispatch:6"
        )
        resilience.reset()
        sched = Scheduler(
            FakeEngine(seed=0, policy="consensus"),
            linger_ms=0, bucket_rows=4, max_queue_rows=4096,
            deadline_ms=0, strict_admission=False, max_dispatch_retries=2,
            watchdog_s=1.5,
            engine_factory=lambda: FakeEngine(seed=0, policy="consensus"),
        )
        obs_alerts.evaluate_now()  # fresh base: wave deltas are wave-only
        errors: List[BaseException] = []

        def one_request():
            try:
                sched.submit_and_wait(
                    ("json",), list(payload), [0.0] * 2, [64] * 2
                )
            except BaseException as e:  # lost futures surface as metrics
                errors.append(e)

        for _wave in range(2):
            threads = [
                threading.Thread(target=one_request) for _ in range(8)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            obs_alerts.evaluate_now()
            ok, _ = obs_alerts.health()
            saw_failing = saw_failing or not ok
        sched.close()
        for _ in range(2):  # quiet cycles: every episode must resolve
            obs_alerts.evaluate_now()
        final_ok, _ = obs_alerts.health()

        # --- verdicts (gathered before the engine is torn down) -------
        moved = obs_counters.delta(before)
        injected = moved.get("chaos.injected", 0)
        if eng is not None:
            by_rule = eng.fired_by_rule()
            evaluations = float(eng.evaluations)
            flaps = float(eng.flaps)
            unresolved = float(len(eng.firing()))
            total_fired, total_resolved = eng.fired, eng.resolved
        else:
            by_rule = {}
            evaluations = flaps = unresolved = 0.0
            total_fired = total_resolved = 0
        chaos_fired = float(sum(by_rule.get(r, 0) for r in expected))
        unexpected = float(total_fired) - chaos_fired - false_pos

        hist = obs_alerts.readiness_history()
        engine_flips = sum(
            1 for h in hist if not h["ready"] and "engine" in h["reasons"]
        )
        readyz_flip = float(
            engine_flips if hist and hist[-1]["ready"] else 0
        )

        # Stop the evaluator and CLOSE the sink (drains the queue) so
        # the JSONL stream can be compared against the engine totals.
        obs_alerts.reset()
        firing_recs = resolved_recs = 0
        manifest_first = False
        try:
            with open(events_path) as f:
                recs = [json.loads(line) for line in f if line.strip()]
            manifest_first = bool(recs) and recs[0].get("event") == "manifest"
            firing_recs = sum(1 for r in recs if r.get("event") == "alert"
                              and r.get("state") == "firing")
            resolved_recs = sum(1 for r in recs if r.get("event") == "alert"
                                and r.get("state") == "resolved")
        except OSError:
            pass  # alerts-off: no engine, no sink, no file
        stream_ok = float(
            manifest_first and total_fired > 0
            and firing_recs == total_fired
            and resolved_recs == total_resolved
        )
    finally:
        for name, prior in (("BCG_TPU_ALERTS", prior_alerts),
                            ("BCG_TPU_ALERT_MS", prior_ms),
                            ("BCG_TPU_ALERT_EVENTS", prior_events),
                            ("BCG_TPU_CHAOS", prior_chaos)):
            if prior is None:
                os.environ.pop(name, None)
            else:
                os.environ[name] = prior
        obs_alerts.reset()
        obs_alerts.reset_readiness()
        resilience.reset()
    if errors:
        raise errors[0]
    return {
        "alerts.rules_evaluated": evaluations,
        "alerts.chaos_alerts_fired": chaos_fired,
        "alerts.fault_coverage": chaos_fired / max(1.0, float(injected)),
        "alerts.false_positives": false_pos,
        "alerts.flaps": flaps,
        "alerts.unresolved_at_end": unresolved,
        "alerts.unexpected_alerts": unexpected,
        "alerts.readyz_flip": readyz_flip,
        "alerts.healthz_flip": float(saw_failing and final_ok),
        "alerts.event_stream_ok": stream_ok,
    }


_RUNNERS = {
    "serve": run_serve_scenario,
    "engine": run_engine_scenario,
    "paged": run_paged_scenario,
    "sampler": run_sampler_scenario,
    "int4": run_int4_scenario,
    "consensus": run_consensus_scenario,
    "fleet": run_fleet_scenario,
    "hostsync": run_hostsync_scenario,
    "megaround": run_megaround_scenario,
    "compile": run_compile_scenario,
    "sweep": run_sweep_scenario,
    "chaos": run_chaos_scenario,
    "scenarios": run_scenarios_scenario,
    "hlo": run_hlo_scenario,
    "alerts": run_alerts_scenario,
}


# ---------------------------------------------------------------- gating
def load_baseline(path: Optional[str] = None) -> Optional[Dict]:
    path = path or baseline_path()
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def _bounds(entry: Dict) -> str:
    op = entry.get("op", "range")
    value = float(entry["value"])
    tol_rel = float(entry.get("tol_rel", 0.0))
    tol_abs = float(entry.get("tol_abs", 0.0))
    slack = abs(value) * tol_rel + tol_abs
    if op == "min":
        return f">= {value - slack:.4g}"
    if op == "max":
        return f"<= {value + slack:.4g}"
    return f"within [{value - slack:.4g}, {value + slack:.4g}]"


def check_metrics(measured: Dict[str, float], baseline: Optional[Dict]) -> List[str]:
    """Findings (empty = green): banded comparison plus the
    load-bearing-baseline contract (unbaselined measured metric and
    stale baseline entry are both failures)."""
    if baseline is None:
        return [f"no baseline file at {baseline_path()} — run "
                "scripts/perf_gate.py --update-baseline"]
    entries = baseline.get("metrics", {})
    findings: List[str] = []
    for name, got in sorted(measured.items()):
        entry = entries.get(name)
        if entry is None:
            findings.append(
                f"{name}: measured {got:.4g} but metric has no entry in "
                "perf_baseline.json — every gated metric needs a "
                "justified baseline (run --update-baseline and add a reason)"
            )
            continue
        op = entry.get("op", "range")
        value = float(entry["value"])
        tol_rel = float(entry.get("tol_rel", 0.0))
        tol_abs = float(entry.get("tol_abs", 0.0))
        slack = abs(value) * tol_rel + tol_abs
        ok = (
            got >= value - slack if op == "min"
            else got <= value + slack if op == "max"
            else value - slack <= got <= value + slack
        )
        if not ok:
            findings.append(
                f"{name}: measured {got:.4g}, required {_bounds(entry)} "
                f"(baseline {value:.4g}, tol_rel={tol_rel}, "
                f"tol_abs={tol_abs}) — {entry.get('reason', 'no reason')}"
            )
    return findings


def check_stale(measured: Dict[str, float], baseline: Optional[Dict],
                scenarios) -> List[str]:
    """Baseline entries whose scenario ran but which nothing measured
    (renamed/dropped metric = stale entry; a SKIPPED scenario's entries
    are not stale)."""
    if baseline is None:
        return []
    prefixes = tuple(f"{s}." for s in scenarios)
    return [
        f"perf_baseline.json entry {name!r} was not produced by its "
        "scenario (stale — remove it, or restore the metric)"
        for name in sorted(baseline.get("metrics", {}))
        if name.startswith(prefixes) and name not in measured
    ]


def update_baseline(measured: Dict[str, float],
                    path: Optional[str] = None) -> str:
    path = path or baseline_path()
    prior = load_baseline(path) or {}
    prior_metrics = prior.get("metrics", {})
    metrics = {}
    for name, got in sorted(measured.items()):
        old = prior_metrics.get(name, {})
        metrics[name] = {
            "value": round(float(got), 6),
            "op": old.get("op", "range"),
            "tol_rel": old.get("tol_rel", 0.15),
            "tol_abs": old.get("tol_abs", 0.0),
            "reason": old.get(
                "reason",
                "pinned by scripts/perf_gate.py --update-baseline; "
                "justify intentional perf changes here",
            ),
        }
    # Entries for scenarios that did not run this time survive untouched.
    for name, entry in prior_metrics.items():
        metrics.setdefault(name, entry)
    data = {
        "_comment": (
            "Hermetic perf-gate baseline (scripts/perf_gate.py). Every "
            "gated metric needs a justified entry; bounds are op "
            "(min/max/range) with tol_rel/tol_abs slack. An unbaselined "
            "measured metric and a stale entry are both gate failures — "
            "the baseline is load-bearing, not a mute "
            "(tests/test_perf_gate.py)."
        ),
        "metrics": dict(sorted(metrics.items())),
    }
    with open(path, "w") as f:
        json.dump(data, f, indent=2)
        f.write("\n")
    return path


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="CPU-hermetic counter-derived perf gate "
        "(FakeEngine serving + tiny real engine + HLO census drift)."
    )
    parser.add_argument("--scenarios", default=",".join(SCENARIOS),
                        help=f"comma list of {SCENARIOS}")
    parser.add_argument("--update-baseline", action="store_true",
                        help="regenerate perf_baseline.json (keeps reasons/bands)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="print measured metrics as JSON")
    parser.add_argument("--inject-regression", default="none",
                        choices=REGRESSIONS,
                        help="self-test: provoke a known regression and "
                        "confirm the gate names it")
    args = parser.parse_args(argv)

    scenarios = tuple(s for s in args.scenarios.split(",") if s)
    bad = [s for s in scenarios if s not in SCENARIOS]
    if bad:
        print(f"unknown scenarios {bad}; known: {SCENARIOS}", file=sys.stderr)
        return 1
    measured: Dict[str, float] = {}
    for s in scenarios:
        measured.update(_RUNNERS[s](args.inject_regression))
    if args.as_json:
        print(json.dumps(measured, indent=2, sort_keys=True))
    else:
        width = max(len(n) for n in measured)
        for name, got in sorted(measured.items()):
            print(f"{name:<{width}}  {got:.4f}")
    if args.update_baseline:
        path = update_baseline(measured)
        print(f"baseline written: {path}", file=sys.stderr)
        return 0
    findings = check_metrics(measured, load_baseline())
    findings += check_stale(measured, load_baseline(), scenarios)
    for f in findings:
        print(f"PERF REGRESSION: {f}", file=sys.stderr)
    if findings:
        return 2
    print("perf gate green", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
