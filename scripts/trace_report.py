#!/usr/bin/env python
"""Latency report from an exported Chrome trace (bcg_tpu.obs.tracer).

``python scripts/trace_report.py TRACE.json [--top N]``

Prints a per-span-name latency table (count / total / p50 / p95, sorted
hottest-first) rebuilt from the trace's B/E and X events, followed by
the top counters the exporter embedded under ``otherData.counters``
(compile/retrace accounting, serve linger buckets).  Self-contained —
no bcg_tpu import — so a trace copied off a TPU host can be read
anywhere; the in-process equivalent is ``tracer.summarize()``.

Note one deliberate asymmetry: ``summarize()`` covers the whole run
(its accumulator is not ring-evicted), while this report sees only the
events that survived the ``BCG_TPU_TRACE_RING`` window.  Unbalanced
events at the ring edge (a B whose E was evicted, or vice versa) are
dropped and counted in the footer rather than silently merged.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from typing import Dict, List, Tuple


def load_trace(path: str) -> dict:
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, list):  # bare event-array form is also legal
        return {"traceEvents": data, "otherData": {}}
    return data


def span_durations(events: List[dict]) -> Tuple[Dict[str, List[float]], int]:
    """{name: [duration_us, ...]} from B/E pairs (per-thread stacks) and
    X events; returns (durations, dropped_unbalanced)."""
    durations: Dict[str, List[float]] = defaultdict(list)
    stacks: Dict[int, List[dict]] = defaultdict(list)
    dropped = 0
    for ev in events:
        ph = ev.get("ph")
        if ph == "X":
            if "dur" in ev:
                durations[ev["name"]].append(float(ev["dur"]))
            continue
        if ph == "B":
            stacks[ev.get("tid", 0)].append(ev)
        elif ph == "E":
            stack = stacks[ev.get("tid", 0)]
            # Pop to the matching B (tolerate ring-evicted partners).
            while stack and stack[-1]["name"] != ev["name"]:
                stack.pop()
                dropped += 1
            if not stack:
                dropped += 1
                continue
            begin = stack.pop()
            durations[ev["name"]].append(
                float(ev["ts"]) - float(begin["ts"])
            )
    dropped += sum(len(s) for s in stacks.values())  # Bs without an E
    return durations, dropped


def _percentile(ordered: List[float], q: float) -> float:
    if not ordered:
        return 0.0
    idx = max(0, min(len(ordered) - 1, int(round(q * (len(ordered) - 1)))))
    return ordered[idx]


def render_report(trace: dict, top: int = 20) -> str:
    events = trace.get("traceEvents", [])
    durations, dropped = span_durations(
        [e for e in events if e.get("ph") in ("B", "E", "X")]
    )
    lines: List[str] = []
    rows = []
    for name, durs in durations.items():
        ordered = sorted(durs)
        total = sum(durs)
        rows.append((
            name, len(durs), total / 1e3,
            _percentile(ordered, 0.50) / 1e3,
            _percentile(ordered, 0.95) / 1e3,
        ))
    rows.sort(key=lambda r: -r[2])
    if rows:
        name_w = max(len("span"), max(len(r[0]) for r in rows))
        lines.append("== span latency (hottest first) ==")
        lines.append(
            f"{'span':<{name_w}}  {'count':>7}  {'total_ms':>10}  "
            f"{'p50_ms':>9}  {'p95_ms':>9}"
        )
        for name, count, total, p50, p95 in rows:
            lines.append(
                f"{name:<{name_w}}  {count:>7}  {total:>10.3f}  "
                f"{p50:>9.3f}  {p95:>9.3f}"
            )
    else:
        lines.append("== span latency: no spans in trace ==")
    if dropped:
        lines.append(
            f"(dropped {dropped} unbalanced event(s) at the ring edge)"
        )
    counters = (trace.get("otherData") or {}).get("counters") or {}
    # engine.hlo.*, hbm.*, engine.hostsync.*, and the compile-cost
    # families (engine.compile_ms.* histograms, engine.retrace_cause.*
    # taxonomy counters, engine.compile_obs.* cumulative totals) get
    # their own sections below, and so do histogram families (the flat
    # .bucket.le_* / .sum / .count entries) — ranked by raw value (op
    # counts, FLOPs, byte totals, cumulative bucket counts, per-span
    # sync tallies, millisecond totals) they would crowd every actual
    # event counter out of the top-N list.
    hist_names = histogram_families(counters)
    ranked = sorted(
        ((k, v) for k, v in counters.items()
         if not k.startswith(("engine.hlo.", "hbm.", "engine.hostsync.",
                              "engine.compile_ms.",
                              "engine.retrace_cause.",
                              "engine.compile_obs.", "alert."))
         and _histogram_owner(k, hist_names) is None),
        key=lambda kv: (-kv[1], kv[0]),
    )[:max(0, top)]
    if ranked:
        lines.append("")
        lines.append(f"== top counters (of {len(counters)}) ==")
        val_w = max(len(f"{v}") for _, v in ranked)
        for name, value in ranked:
            lines.append(f"{value:>{val_w}}  {name}")
    spec_line = spec_acceptance(counters)
    if spec_line:
        lines.append("")
        lines.append(spec_line)
    prefill_line = prefill_positions(counters)
    if prefill_line:
        lines.append("")
        lines.append(prefill_line)
    hist = histogram_table(counters, hist_names)
    if hist:
        lines.append("")
        lines.append(hist)
    hbm = hbm_ledger_section(counters)
    if hbm:
        lines.append("")
        lines.append(hbm)
    census = hlo_census_table(counters)
    if census:
        lines.append("")
        lines.append(census)
    fused = fused_sampler_section(counters)
    if fused:
        lines.append("")
        lines.append(fused)
    hostsync = hostsync_section(counters)
    if hostsync:
        lines.append("")
        lines.append(hostsync)
    fusion_line = round_fusion_line(counters)
    if fusion_line:
        lines.append("")
        lines.append(fusion_line)
    compile_time = compile_time_section(counters)
    if compile_time:
        lines.append("")
        lines.append(compile_time)
    causes = retrace_cause_section(counters)
    if causes:
        lines.append("")
        lines.append(causes)
    alert_line = alerts_section(counters)
    if alert_line:
        lines.append("")
        lines.append(alert_line)
    return "\n".join(lines)


def histogram_families(counters: Dict[str, float]) -> List[str]:
    """Histogram base names reconstructed from the registry's flat form
    (``<name>.bucket.le_<bound>`` siblings of ``<name>.sum`` /
    ``<name>.count``), longest-first so nested prefixes resolve to the
    most specific owner."""
    names = {
        k.split(".bucket.le_", 1)[0]
        for k in counters if ".bucket.le_" in k
    }
    return sorted(names, key=len, reverse=True)


def _histogram_owner(key: str, families: List[str]) -> str:
    """The histogram family ``key`` belongs to, or None — used both to
    keep raw bucket/sum/count entries out of the ranked counter list and
    to rebuild per-family quantiles."""
    for name in families:
        if (key.startswith(name + ".bucket.le_")
                or key == name + ".sum" or key == name + ".count"):
            return name
    return None


def _parse_bound(label: str) -> float:
    """``le_`` label -> float bound (``25`` -> 25.0, ``2_5`` -> 2.5 —
    the registry's bound_label encoding, reimplemented here to keep the
    report bcg_tpu-import-free)."""
    return float(label.replace("_", "."))


def _quantile_from_cumulative(
    buckets: List[Tuple[float, float]], total: float, q: float
) -> float:
    """Prometheus histogram_quantile over cumulative (bound, count)
    pairs: linear interpolation inside the target bucket, clamped to the
    highest finite bound for overflow-bucket ranks."""
    if total <= 0:
        return 0.0
    target = q * total
    prev_bound, prev_cum = 0.0, 0.0
    for bound, cum in buckets:
        if cum >= target and cum > prev_cum:
            frac = (target - prev_cum) / (cum - prev_cum)
            return prev_bound + (bound - prev_bound) * max(0.0, min(1.0, frac))
        prev_bound, prev_cum = bound, cum
    return buckets[-1][0] if buckets else 0.0


def histogram_table(counters: Dict[str, float],
                    families: List[str]) -> str:
    """Per-histogram quantile table (count / p50 / p95 / p99, bucket-
    interpolated) rebuilt from the flat registry entries, or '' when the
    export carries no histograms."""
    if not families:
        return ""
    rows = []
    for name in sorted(families):
        prefix = name + ".bucket.le_"
        buckets = sorted(
            (_parse_bound(k[len(prefix):]), v)
            for k, v in counters.items() if k.startswith(prefix)
        )
        total = counters.get(name + ".count", buckets[-1][1] if buckets else 0)
        rows.append((
            name, int(total),
            _quantile_from_cumulative(buckets, total, 0.50),
            _quantile_from_cumulative(buckets, total, 0.95),
            _quantile_from_cumulative(buckets, total, 0.99),
        ))
    name_w = max(len("histogram"), max(len(r[0]) for r in rows))
    lines = ["== histogram quantiles (bucket-interpolated) =="]
    lines.append(
        f"{'histogram':<{name_w}}  {'count':>7}  {'p50':>9}  "
        f"{'p95':>9}  {'p99':>9}"
    )
    for name, count, p50, p95, p99 in rows:
        lines.append(
            f"{name:<{name_w}}  {count:>7}  {p50:>9.3f}  "
            f"{p95:>9.3f}  {p99:>9.3f}"
        )
    return "\n".join(lines)


def hbm_ledger_section(counters: Dict[str, float]) -> str:
    """Compact hbm.* gauge listing (bcg_tpu/obs/ledger.py accounts), or
    '' when the export carries none."""
    rows = sorted(
        (k, v) for k, v in counters.items() if k.startswith("hbm.")
    )
    if not rows:
        return ""
    name_w = max(len(k) for k, _ in rows)
    lines = ["== hbm ledger gauges =="]
    for name, value in rows:
        lines.append(f"{name:<{name_w}}  {value:>16.0f}")
    return "\n".join(lines)


def hlo_census_table(counters: Dict[str, float]) -> str:
    """Per-jit-entry kernel-census table rebuilt from the exported
    ``engine.hlo.<entry>.<metric>`` gauges (bcg_tpu/obs/hlo.py), or ''
    when the export carries none.  Kept bcg_tpu-import-free like the
    rest of this report: the gauge names alone define the schema."""
    rows: Dict[str, Dict[str, float]] = {}
    for name, value in counters.items():
        if not name.startswith("engine.hlo."):
            continue
        rest = name[len("engine.hlo."):]
        entry, _, metric = rest.rpartition(".")
        if entry:
            rows.setdefault(entry, {})[metric] = value
    if not rows:
        return ""
    cols = ("fusions", "custom_calls", "collectives", "step_ops",
            "step_fusions", "total_ops", "flops", "bytes_accessed")
    name_w = max(len("jit entry"), max(len(e) for e in rows))
    lines = ["== hlo kernel census (engine.hlo.* gauges) =="]
    lines.append(
        f"{'jit entry':<{name_w}}  " + "  ".join(f"{c:>14}" for c in cols)
    )
    for entry in sorted(rows):
        vals = []
        for c in cols:
            v = rows[entry].get(c)
            vals.append("-" if v is None else f"{v:.0f}")
        lines.append(
            f"{entry:<{name_w}}  " + "  ".join(f"{v:>14}" for v in vals)
        )
    return "\n".join(lines)


def fused_sampler_section(counters: Dict[str, float]) -> str:
    """Per-decode-loop-family fused-sampler comparison rebuilt from the
    TPU cross-lowering twin gauges (``engine.hlo.tpu_<family>.*`` vs
    ``engine.hlo.tpu_fused_<family>.*``): one step-custom-call line per
    family showing the per-decode-step op count moving DOWN under the
    fused kernel — the at-a-glance form of the census acceptance
    inequality; '' when the export carries no twin pair."""
    prefix = "engine.hlo.tpu_fused_"
    families = sorted({
        name[len(prefix):].split(".")[0]
        for name in counters if name.startswith(prefix)
    })
    rows = []
    for fam in families:
        xla_ops = counters.get(f"engine.hlo.tpu_{fam}.step_ops")
        fused_ops = counters.get(f"{prefix}{fam}.step_ops")
        if xla_ops is None or fused_ops is None:
            continue
        cc = counters.get(f"{prefix}{fam}.step_custom_calls", 0)
        rows.append((fam, xla_ops, fused_ops, cc))
    if not rows:
        return ""
    name_w = max(len("decode-loop family"), max(len(r[0]) for r in rows))
    lines = ["== fused guided sampler (TPU cross-lowering twins) =="]
    lines.append(
        f"{'decode-loop family':<{name_w}}  {'step_ops xla':>12}  "
        f"{'step_ops fused':>14}  {'step custom-calls':>17}"
    )
    for fam, xla_ops, fused_ops, cc in rows:
        lines.append(
            f"{fam:<{name_w}}  {xla_ops:>12.0f}  {fused_ops:>14.0f}  "
            f"{cc:>17.0f}"
        )
    return "\n".join(lines)


def hostsync_section(counters: Dict[str, float]) -> str:
    """Host-syncs-by-span attribution table rebuilt from the exported
    ``engine.hostsync.span.*`` counters (bcg_tpu/obs/hostsync.py), with
    a totals footer (attributed/total coverage), or '' when the export
    carries no audit.  Kept bcg_tpu-import-free like the rest of this
    report: the counter names alone define the schema."""
    prefix = "engine.hostsync.span."
    rows = sorted(
        ((k[len(prefix):], v) for k, v in counters.items()
         if k.startswith(prefix)),
        key=lambda kv: (-kv[1], kv[0]),
    )
    total = counters.get("engine.hostsync.total", 0)
    if not rows and not total:
        return ""
    lines = ["== host syncs by span (engine.hostsync.*) =="]
    if rows:
        name_w = max(len("span"), max(len(r[0]) for r in rows))
        lines.append(f"{'span':<{name_w}}  {'syncs':>8}")
        for name, value in rows:
            lines.append(f"{name:<{name_w}}  {value:>8.0f}")
    attributed = counters.get("engine.hostsync.attributed", 0)
    coverage = f" ({100.0 * attributed / total:.1f}% attributed)" if total else ""
    lines.append(
        f"total {total:.0f} sync(s), {attributed:.0f} attributed{coverage}"
    )
    return "\n".join(lines)


def compile_time_section(counters: Dict[str, float]) -> str:
    """'compile time by entry' table rebuilt from the exported
    ``engine.compile_ms.<entry>`` histogram flats plus the
    ``engine.compile.<entry>`` / ``engine.retrace.<entry>`` counters
    (bcg_tpu/obs/compile.py), hottest first by total ms, or '' when the
    export carries no compile observability.  Kept bcg_tpu-import-free
    like the rest of this report: the counter names alone define the
    schema (``scripts/compile_report.py`` is the standalone form)."""
    prefix = "engine.compile_ms."
    rows: Dict[str, Dict[str, float]] = {}
    for name, value in counters.items():
        if not name.startswith(prefix):
            continue
        rest = name[len(prefix):]
        if rest.endswith(".sum"):
            rows.setdefault(rest[:-len(".sum")], {})["total_ms"] = value
        elif rest.endswith(".count"):
            rows.setdefault(rest[:-len(".count")], {})["count"] = value
    if not rows:
        return ""
    name_w = max(len("jit entry"), max(len(e) for e in rows))
    lines = ["== compile time by entry (engine.compile_ms.*) =="]
    lines.append(
        f"{'jit entry':<{name_w}}  {'compiles':>8}  {'retraces':>8}  "
        f"{'timed':>6}  {'total_ms':>10}"
    )
    for entry, row in sorted(rows.items(),
                             key=lambda kv: -kv[1].get("total_ms", 0.0)):
        compiles = counters.get(f"engine.compile.{entry}", 0)
        retraces = counters.get(f"engine.retrace.{entry}", 0)
        lines.append(
            f"{entry:<{name_w}}  {compiles:>8.0f}  {retraces:>8.0f}  "
            f"{row.get('count', 0):>6.0f}  {row.get('total_ms', 0.0):>10.1f}"
        )
    first = counters.get("engine.compile_obs.first_compile_ms", 0)
    retrace_ms = counters.get("engine.compile_obs.retrace_ms", 0)
    aot = counters.get("engine.compile_obs.aot_ms", 0)
    lines.append(
        f"cumulative: {first:.1f} ms first-compile, {retrace_ms:.1f} ms "
        f"retrace, {aot:.1f} ms census-AOT; "
        f"{counters.get('engine.compile_obs.cache_entries', 0):.0f} "
        "trace-cache entries"
    )
    return "\n".join(lines)


def retrace_cause_section(counters: Dict[str, float]) -> str:
    """'retraces by cause' table from the exported
    ``engine.retrace_cause.<kind>`` taxonomy counters, or '' when the
    export carries none."""
    prefix = "engine.retrace_cause."
    rows = sorted(
        ((k[len(prefix):], v) for k, v in counters.items()
         if k.startswith(prefix)),
        key=lambda kv: (-kv[1], kv[0]),
    )
    if not rows:
        return ""
    name_w = max(len("cause"), max(len(r[0]) for r in rows))
    lines = ["== retraces by cause (engine.retrace_cause.*) =="]
    lines.append(f"{'cause':<{name_w}}  {'retraces':>8}")
    for name, value in rows:
        lines.append(f"{name:<{name_w}}  {value:>8.0f}")
    return "\n".join(lines)


def round_fusion_line(counters: Dict[str, float]) -> str:
    """One-line fused mega-round summary when the export carries fused
    rounds (engine.megaround.rounds); '' otherwise.  Syncs/round comes
    from the game.host_syncs per-round histogram flats — 1.0 on the
    fused path (one packed readback per round) vs 6.0 lockstep, the
    ROADMAP item 1 headline."""
    fused = counters.get("engine.megaround.rounds")
    if not fused:
        return ""
    rounds = counters.get("game.host_syncs.count", 0)
    syncs = counters.get("game.host_syncs.sum", 0)
    per_round = f", {syncs / rounds:.1f} sync(s)/round" if rounds else ""
    return (
        f"== round fusion: {fused:.0f} fused round(s) — one jit entry "
        f"per consensus round{per_round} =="
    )


def spec_acceptance(counters: Dict[str, float]) -> str:
    """One-line draft acceptance summary when the export carries
    speculative-decoding counters (engine.spec.*); '' otherwise."""
    drafted = counters.get("engine.spec.drafted")
    if not drafted:
        return ""
    accepted = counters.get("engine.spec.accepted", 0)
    return (
        f"== speculative decoding: {accepted}/{drafted} draft tokens "
        f"accepted ({100.0 * accepted / drafted:.1f}%) =="
    )


def prefill_positions(counters: Dict[str, float]) -> str:
    """One-line real-vs-padded prefill position summary
    (engine.prefill.positions_*); '' when the export carries neither.
    Real positions are actual prompt-token work — prefix-cache savings
    show up here without pad noise; the padded total is what the FLOP
    bill sees."""
    padded = counters.get("engine.prefill.positions_padded")
    if not padded:
        return ""
    real = counters.get("engine.prefill.positions_real", 0)
    return (
        f"== prefill positions: {int(real)} real / {int(padded)} padded "
        f"({100.0 * real / padded:.1f}% real work) =="
    )


def alerts_section(counters: Dict[str, float]) -> str:
    """One-line alert-plane summary when the export carries alert.*
    transition counters (BCG_TPU_ALERTS); '' otherwise.  The alert.*
    family is excluded from the ranked top-counter list above — its
    evaluation counter grows once per cycle and would crowd real event
    counters out — so this line is where the plane surfaces."""
    evaluations = counters.get("alert.evaluations")
    if not evaluations:
        return ""
    fired = int(counters.get("alert.fired", 0))
    resolved = int(counters.get("alert.resolved", 0))
    flaps = int(counters.get("alert.flaps", 0))
    firing = sorted(
        k[len("alert.firing."):] for k, v in counters.items()
        if k.startswith("alert.firing.") and v
    )
    line = (
        f"== alerts: {fired} fired / {resolved} resolved over "
        f"{int(evaluations)} evaluation(s), {flaps} flap(s)"
    )
    line += f"; firing: {', '.join(firing)} ==" if firing else " =="
    return line


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Latency table + top counters from a bcg_tpu Chrome "
        "trace export (BCG_TPU_TRACE_OUT / tracer.export())."
    )
    parser.add_argument("trace", help="path to the exported trace JSON")
    parser.add_argument("--top", type=int, default=20,
                        help="counters to show (default 20)")
    args = parser.parse_args(argv)
    try:
        trace = load_trace(args.trace)
    except (OSError, json.JSONDecodeError) as exc:
        print(f"trace_report: cannot read {args.trace}: {exc}",
              file=sys.stderr)
        return 1
    print(render_report(trace, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
