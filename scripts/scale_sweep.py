#!/usr/bin/env python
"""One-agent-per-chip scale sweep through the REAL serving stack.

BASELINE config 4's shape ("Scale sweep: 16/32/64 agents, one-agent-per-
chip on v5e-64"): N agents play a full Byzantine Consensus Game through
``BCGSimulation`` -> ``JaxEngine(dp=N)`` — every decision/vote batch is
one [N, ...] device batch SHARDED one-row-per-chip over the mesh's `dp`
axis (engine._put_batch), and the broadcast/receive phase is one
``all_gather`` over the same mesh (--spmd-exchange path).  The reference
runs its scale sweep by queueing agents through one vLLM server
(vllm_agent.py batching); here agent parallelism IS the mesh layout.

Since the sweep tier landed this script is a THIN WRAPPER over a
one-job :mod:`bcg_tpu.sweep` run (the game goes through the shared
serving scheduler as a tenant, and the sweep manifest — fleet-identity-
stamped like every JSONL sink — lands in --sweep-dir); the emitted JSON
line is byte-compatible with the pre-wrapper schema, pinned by
``tests/test_scale_sweep.py``.

Hermetic run on a virtual device mesh (no TPU pod needed):

    XLA_FLAGS=--xla_force_host_platform_device_count=16 \
        python scripts/scale_sweep.py --agents 16 --rounds 4

Emits ONE JSON line: {agents, devices, dp, rounds, rounds_per_sec,
decisions_per_sec, dp_batches, dp_bypasses, sp_bypasses, consensus}.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--agents", type=int, default=16,
                    help="total agents; byzantine count is agents//4")
    ap.add_argument("--rounds", type=int, default=4, help="max game rounds")
    ap.add_argument("--model", default="bcg-tpu/tiny-test")
    ap.add_argument("--max-model-len", type=int, default=512)
    ap.add_argument("--decide-tokens", type=int, default=48)
    ap.add_argument("--vote-tokens", type=int, default=32)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--sweep-dir", default=None,
                    help="sweep dir for the manifest/events (default: a "
                    "fresh temp dir — this script is a metrics probe)")
    args = ap.parse_args()

    # Honour a virtual-device request BEFORE backend init (this
    # container's axon sitecustomize force-registers the TPU platform,
    # so the env var alone is not enough — same dance as
    # __graft_entry__.dryrun_multichip).
    if "xla_force_host_platform_device_count" in os.environ.get("XLA_FLAGS", ""):
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    n_dev = len(jax.devices())
    dp = next(d for d in range(min(args.agents, n_dev), 0, -1)
              if args.agents % d == 0)

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from bcg_tpu.sweep import run_sweep

    n_byz = args.agents // 4
    spec = {
        "name": f"scale-{args.agents}",
        "base": {
            "agents": args.agents,
            "byzantine": n_byz,
            "max_rounds": args.rounds,
            "seed": args.seed,
            "backend": "jax",
            "model": args.model,
            "max_model_len": args.max_model_len,
            "data_parallel_size": dp,
            "spmd_exchange": True,
            "decide_tokens": args.decide_tokens,
            "vote_tokens": args.vote_tokens,
        },
        "axes": {},
    }
    out_dir = args.sweep_dir or tempfile.mkdtemp(prefix="bcg-scale-sweep-")
    summary = run_sweep(spec, out_dir, max_concurrent=1, linger_ms=0)
    if summary["failed"]:
        print(json.dumps(summary, default=str), file=sys.stderr)
        return 1
    if summary["results"]:
        job = summary["results"][0]
    else:
        # Resume path: the job already completed in this --sweep-dir on
        # a previous invocation — rebuild the row from its persisted
        # manifest record instead of failing an all-skipped rerun.
        from bcg_tpu.sweep import completed_job_ids, expand

        jid = expand(spec)[0].job_id
        job = completed_job_ids(out_dir).get(jid)
        if job is None:
            print(json.dumps(summary, default=str), file=sys.stderr)
            return 1
        print(
            f"scale_sweep: job {jid} already completed in {out_dir}; "
            "reporting the recorded result (use a fresh --sweep-dir to "
            "re-measure)",
            file=sys.stderr,
        )
    eng = job.get("engine") or {}
    # Legacy schema — byte-compatible with the pre-sweep-tier script
    # (tests/test_scale_sweep.py pins every key).
    row = {
        "agents": args.agents,
        "devices": n_dev,
        "dp": dp,
        "model": args.model,
        "rounds": job.get("rounds", 0),
        "rounds_per_sec": job.get("rounds_per_sec", 0.0),
        "decisions_per_sec": job.get("decisions_per_sec", 0.0),
        "dp_batches": eng.get("dp_batches"),
        "dp_bypasses": eng.get("dp_bypasses"),
        "sp_bypasses": eng.get("sp_bypasses"),
        "spmd_mesh_dp": job.get("spmd_mesh_dp"),
        "consensus": bool(job.get("converged")),
    }
    print(json.dumps(row))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
