#!/usr/bin/env python
"""One-agent-per-chip scale sweep through the REAL serving stack.

BASELINE config 4's shape ("Scale sweep: 16/32/64 agents, one-agent-per-
chip on v5e-64"): N agents play a full Byzantine Consensus Game through
``BCGSimulation`` -> ``JaxEngine(dp=N)`` — every decision/vote batch is
one [N, ...] device batch SHARDED one-row-per-chip over the mesh's `dp`
axis (engine._put_batch), and the broadcast/receive phase is one
``all_gather`` over the same mesh (--spmd-exchange path).  The reference
runs its scale sweep by queueing agents through one vLLM server
(vllm_agent.py batching); here agent parallelism IS the mesh layout.

Hermetic run on a virtual device mesh (no TPU pod needed):

    XLA_FLAGS=--xla_force_host_platform_device_count=16 \
        python scripts/scale_sweep.py --agents 16 --rounds 4

Emits ONE JSON line: {agents, devices, dp, rounds, rounds_per_sec,
decisions_per_sec, dp_batches, dp_bypasses, sp_bypasses, consensus}.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--agents", type=int, default=16,
                    help="total agents; byzantine count is agents//4")
    ap.add_argument("--rounds", type=int, default=4, help="max game rounds")
    ap.add_argument("--model", default="bcg-tpu/tiny-test")
    ap.add_argument("--max-model-len", type=int, default=512)
    ap.add_argument("--decide-tokens", type=int, default=48)
    ap.add_argument("--vote-tokens", type=int, default=32)
    ap.add_argument("--seed", type=int, default=7)
    args = ap.parse_args()

    # Honour a virtual-device request BEFORE backend init (this
    # container's axon sitecustomize force-registers the TPU platform,
    # so the env var alone is not enough — same dance as
    # __graft_entry__.dryrun_multichip).
    if "xla_force_host_platform_device_count" in os.environ.get("XLA_FLAGS", ""):
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    n_dev = len(jax.devices())
    dp = next(d for d in range(min(args.agents, n_dev), 0, -1)
              if args.agents % d == 0)

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from bcg_tpu.config import BCGConfig
    from bcg_tpu.runtime.orchestrator import BCGSimulation

    base = BCGConfig()
    n_byz = args.agents // 4
    cfg = dataclasses.replace(
        base,
        game=dataclasses.replace(
            base.game, num_honest=args.agents - n_byz, num_byzantine=n_byz,
            max_rounds=args.rounds, seed=args.seed,
        ),
        network=dataclasses.replace(base.network, spmd_exchange=True),
        engine=dataclasses.replace(
            base.engine, backend="jax", model_name=args.model,
            max_model_len=args.max_model_len, data_parallel_size=dp,
        ),
        llm=dataclasses.replace(
            base.llm, max_tokens_decide=args.decide_tokens,
            max_tokens_vote=args.vote_tokens,
        ),
        metrics=dataclasses.replace(
            base.metrics, save_results=False, generate_plots=False,
        ),
    )
    sim = BCGSimulation(config=cfg)
    try:
        stats = sim.run()
    finally:
        sim.close()
    perf = sim.profiler.summary()
    eng = sim.engine
    row = {
        "agents": args.agents,
        "devices": n_dev,
        "dp": dp,
        "model": args.model,
        "rounds": stats["total_rounds"],
        "rounds_per_sec": round(perf["rounds_per_sec"], 4),
        "decisions_per_sec": round(perf["decisions_per_sec"], 4),
        "dp_batches": eng.dp_batches,
        "dp_bypasses": eng.dp_bypasses,
        "sp_bypasses": eng.sp_bypasses,
        "spmd_mesh_dp": (sim._spmd_mesh.shape.get("dp")
                         if sim._spmd_mesh is not None else None),
        "consensus": stats["consensus_reached"],
    }
    print(json.dumps(row))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
