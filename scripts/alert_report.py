#!/usr/bin/env python
"""Merge per-rank alert JSONL streams into one firing timeline.

Input files are ``BCG_TPU_ALERT_EVENTS`` sinks (first line = run
manifest, then one record per firing/resolved transition) from any
number of ranks and runs — plus alert-shaped records other tools emit
into the same schema (``scripts/bench_trajectory.py --alert-out``
writes its rc-2 perf regressions this way, so cross-run regressions
and runtime alerts land on ONE timeline).

Output: a chronological transition timeline (one line per event,
stamped with run id, rank, severity) followed by a per-run/rule
summary (fired / resolved / still-firing counts, flap detection — a
rule that fired again after resolving).

Deliberately import-free of bcg_tpu (stdlib only): must run on a
laptop against files scp'd from a fleet.  Torn tail lines (a rank
killed mid-write) are skipped, like every other sink reader here.

Usage:
  python scripts/alert_report.py alerts-*.jsonl
  python scripts/alert_report.py --severity page merged/*.jsonl
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Any, Dict, List

SEVERITY_ORDER = {"info": 0, "warn": 1, "page": 2}


def load_records(paths: List[str]) -> List[Dict[str, Any]]:
    """Parse every file: each record is annotated with the run id and
    rank its file's manifest header declared (``?`` when a file has no
    manifest — e.g. a stream still being written, or a tool that emits
    bare alert records)."""
    records: List[Dict[str, Any]] = []
    for path in paths:
        run_id, rank = "?", "?"
        try:
            fh = open(path, encoding="utf-8")
        except OSError as exc:
            print(f"alert_report: cannot read {path}: {exc}",
                  file=sys.stderr)
            continue
        with fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail: a rank died mid-write
                if rec.get("event") == "manifest":
                    run_id = str(rec.get("run_id", "?"))
                    rank = rec.get("process_index", "?")
                    continue
                if rec.get("event") != "alert":
                    continue
                rec.setdefault("run_id", run_id)
                rec.setdefault("rank", rank)
                records.append(rec)
    records.sort(key=lambda r: (r.get("ts", 0), str(r.get("rule", ""))))
    return records


def render_timeline(records: List[Dict[str, Any]]) -> str:
    lines = ["== alert timeline =="]
    for r in records:
        ts = r.get("ts")
        stamp = (time.strftime("%H:%M:%S", time.gmtime(ts))
                 + f".{int((ts % 1) * 1000):03d}") if ts else "??:??:??"
        arrow = "FIRING " if r.get("state") == "firing" else "resolved"
        value = r.get("value")
        val = f" value={value}" if value is not None else ""
        lines.append(
            f"{stamp}  run={r.get('run_id')} rank={r.get('rank')} "
            f"[{r.get('severity', '?'):<4}] {arrow} {r.get('rule')}"
            f"{val}  {r.get('summary', '')}".rstrip()
        )
    return "\n".join(lines)


def summarize(records: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Per (run, rule, severity) rollup in firing order: fired/resolved
    counts, whether the rule is STILL firing at its stream's end, and
    flaps (re-fires after a resolve — the debounce's failure mode)."""
    rollup: Dict[Any, Dict[str, Any]] = {}
    for r in records:
        key = (r.get("run_id"), r.get("rank"), r.get("rule"),
               r.get("severity"))
        row = rollup.setdefault(key, {
            "run_id": key[0], "rank": key[1], "rule": key[2],
            "severity": key[3], "fired": 0, "resolved": 0, "flaps": 0,
            "firing_now": False,
        })
        if r.get("state") == "firing":
            if row["fired"]:
                row["flaps"] += 1
            row["fired"] += 1
            row["firing_now"] = True
        elif r.get("state") == "resolved":
            row["resolved"] += 1
            row["firing_now"] = False
    return sorted(
        rollup.values(),
        key=lambda row: (-SEVERITY_ORDER.get(row["severity"], -1),
                         str(row["run_id"]), str(row["rule"])),
    )


def render_summary(rows: List[Dict[str, Any]]) -> str:
    lines = ["== per-run rule summary =="]
    for row in rows:
        state = "STILL FIRING" if row["firing_now"] else "all resolved"
        flap = f", {row['flaps']} flap(s)" if row["flaps"] else ""
        lines.append(
            f"run={row['run_id']} rank={row['rank']} "
            f"[{row['severity']:<4}] {row['rule']}: "
            f"{row['fired']} fired / {row['resolved']} resolved "
            f"({state}{flap})"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Merge alert JSONL files into one firing timeline."
    )
    parser.add_argument("paths", nargs="+",
                        help="alert JSONL files (any ranks, any runs)")
    parser.add_argument("--severity", choices=sorted(SEVERITY_ORDER),
                        help="only transitions at (or above) this severity")
    args = parser.parse_args(argv)
    records = load_records(args.paths)
    if args.severity:
        floor = SEVERITY_ORDER[args.severity]
        records = [r for r in records
                   if SEVERITY_ORDER.get(r.get("severity"), -1) >= floor]
    if not records:
        print("alert_report: no alert transitions in "
              f"{len(args.paths)} file(s)")
        return 0
    print(render_timeline(records))
    print()
    rows = summarize(records)
    print(render_summary(rows))
    still = [row for row in rows if row["firing_now"]]
    if still:
        print()
        print(f"({len(still)} rule(s) still firing at stream end)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
