#!/usr/bin/env python
"""HLO kernel census over the engine's jit entries, hermetically on CPU.

Builds tiny hermetic engines (``bcg-tpu/tiny-test``), enables the
census recorder (``bcg_tpu/obs/hlo.py``), drives one deterministic
guided generation per decode-loop family (plain / fast-forward /
speculative), and prints the per-entry op census — fusions,
custom-calls, collectives, scatter/gather, per-decode-step kernel
counts — plus XLA cost-analysis FLOPs and bytes-accessed.  This is
ROADMAP item 5's acceptance instrument: any Pallas fusion work must
move ``decode_loop.step_fusions``/``step_ops`` DOWN, and nothing may
move them up unnoticed.

Drift gate: ``--check`` compares the census against the checked-in
``hlo_baseline.json`` (same justified-entry idiom as
``lint_baseline.json`` — every entry carries a reason, a censused entry
missing from the baseline is a finding, a baseline entry the scenario
no longer exercises is a stale-entry finding) and exits non-zero on any
drift, so it composes with ``set -o pipefail`` harnesses and tier-1
(``tests/test_hlo_census.py`` runs the same comparison in-process).
``--update-baseline`` regenerates the file, PRESERVING existing
reasons.

Usage:
    python scripts/hlo_census.py                 # print the table
    python scripts/hlo_census.py --check         # drift-gate (rc 2 on drift)
    python scripts/hlo_census.py --update-baseline
    python scripts/hlo_census.py --json          # machine-readable census
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ARMS = ("plain", "ff", "spec", "paged", "paged_pallas", "fused", "megaround")
_MODEL = "bcg-tpu/tiny-test"
_SCHEMA = {
    "type": "object",
    "properties": {"value": {"type": "integer", "minimum": 0, "maximum": 100}},
    "required": ["value"],
}
# Deterministic two-row scenario: one system prefix (prefix-cache path
# compiles prefill_suffix too) + a short round prompt; temperature 0.
_PROMPTS = [
    ("You are agent_1 in a consensus game.",
     "Round 1. agent_2 value: 41. Your current value: 42. Decide."),
    ("You are agent_2 in a consensus game.",
     "Round 1. agent_1 value: 42. Your current value: 41. Decide."),
]


def baseline_path() -> str:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(root, "hlo_baseline.json")


def _force_cpu() -> None:
    # Hermetic: the census pins CPU-lowered programs (this environment's
    # sitecustomize force-registers TPU, so the env var alone is not
    # enough — same dance as bench.py's BENCH_FORCE_CPU).  Pin the same
    # 8-device virtual CPU mesh tests/conftest.py forces: XLA's fusion
    # decisions depend on the host-platform device count, so the
    # baseline is only comparable to tier-1's in-process census if both
    # lower under identical geometry.
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")


def run_scenario(arms=ARMS) -> Dict[str, Dict]:
    """Drive the census scenario and return ``hlo.snapshot()``.

    One tiny engine per decode-loop family; entries shared between arms
    (the prefill family) record from whichever arm runs first — arm
    order is fixed, so the census is deterministic.
    """
    _force_cpu()
    from bcg_tpu.config import BCGConfig
    from bcg_tpu.engine.jax_engine import JaxEngine
    from bcg_tpu.obs import hlo as obs_hlo

    obs_hlo.enable(True)
    base = BCGConfig().engine
    for arm in arms:
        if arm == "megaround":
            _run_megaround_arm(base)
            continue
        cfg = dataclasses.replace(
            base,
            model_name=_MODEL,
            backend="jax",
            max_model_len=512,
            decode_fast_forward=(arm == "ff"),
            spec_decode=(arm == "spec"),
            # The paged arms lower the block-gather/scatter programs
            # under their own entry names (prefill_paged /
            # paged_decode_loop / paged_pallas_decode_loop) so the
            # dense entries never drift.  The paged_pallas arm runs the
            # fused kernel in interpret mode (this census is CPU) — its
            # step counts are gated strictly BELOW the gather arm's
            # (tests/test_hlo_census.py), the ISSUE-8 acceptance hook.
            paged_kv=arm.startswith("paged"),
            paged_kv_impl=("pallas" if arm == "paged_pallas" else "auto"),
            # The fused arm EXECUTES the fused-sampler plain loop (the
            # kernel's interpret-mode emulation on this CPU census —
            # its entry pins under fused_decode_loop with a NOT-kernels
            # reason, like paged_pallas).  The hardware inequality —
            # step ops strictly DOWN under the fused sampler for all
            # three loop families — is carried by the tpu_*/tpu_fused_*
            # cross-lowering twin entries the dense arms record
            # (engine._maybe_record_sampler_tpu_lowering).
            fused_sampler=("pallas" if arm == "fused" else "auto"),
        )
        engine = JaxEngine(cfg)
        try:
            engine.batch_generate_json(
                [(sysp, user, _SCHEMA) for sysp, user in _PROMPTS],
                temperature=0.0, max_tokens=24,
            )
        finally:
            engine.shutdown()
    return obs_hlo.snapshot()


def _run_megaround_arm(base) -> None:
    """One fused consensus round (ROADMAP item 1): pins the whole-round
    program under the ``megaround`` entry — guided decode loops for both
    phases, the DFA decision parse, the masked-matmul exchange, and the
    vote tally all lower into ONE jit module, so a kernel added anywhere
    in the round shows up here.  Also records the per-phase
    static-prefix ``prefill_suffix``-style programs the plan caches
    (``prefill`` family — shared entry, first arm to run wins)."""
    import dataclasses as _dc

    import numpy as np

    from bcg_tpu.engine.jax_engine import JaxEngine

    cfg = _dc.replace(
        base, model_name=_MODEL, backend="jax", max_model_len=2048,
    )
    engine = JaxEngine(cfg)
    try:
        n = 2
        plan = engine.prepare_megaround(
            n_agents=n, lo=0, hi=100, max_rounds=6,
        )
        mask = np.ones((n, n), bool)
        np.fill_diagonal(mask, False)
        engine.run_megaround(
            plan,
            np.asarray([42, 41], np.int32),
            np.full((n, n), -1, np.int32),
            1,
            mask,
            np.zeros(n, bool),
            np.asarray([42, 41], np.int32),
        )
    finally:
        engine.shutdown()


# ---------------------------------------------------------------- baseline
def load_baseline(path: Optional[str] = None) -> Optional[Dict]:
    path = path or baseline_path()
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def check_drift(census: Dict[str, Dict], baseline: Optional[Dict]) -> List[str]:
    """Findings (empty = green) comparing a census against the baseline.

    Count metrics compare EXACTLY (op counts of a fixed program on a
    fixed backend are deterministic; one added kernel in the decode step
    must fail).  flops / bytes_accessed compare within the baseline's
    relative tolerance (default 10%) — cost-model outputs, pinned
    loosely on purpose.
    """
    from bcg_tpu.obs.hlo import COUNT_METRICS

    if baseline is None:
        return [f"no baseline file at {baseline_path()} — run "
                "scripts/hlo_census.py --update-baseline"]
    findings: List[str] = []
    import jax

    backend = jax.default_backend()
    if baseline.get("backend") != backend:
        return [
            f"baseline was recorded on backend {baseline.get('backend')!r} "
            f"but this census ran on {backend!r} — not comparable; "
            "regenerate with --update-baseline on the target backend"
        ]
    version_note = ""
    if baseline.get("jax_version") != jax.__version__:
        version_note = (
            f" [note: baseline jax {baseline.get('jax_version')}, running "
            f"{jax.__version__} — a compiler upgrade may legitimately "
            "shift counts; regenerate if every entry moved]"
        )
    entries = baseline.get("entries", {})
    for entry, recorded in sorted(census.items()):
        if "error" in recorded:
            findings.append(
                f"{entry}: census recording failed: {recorded['error']}"
            )
            continue
        pinned = entries.get(entry)
        if pinned is None:
            findings.append(
                f"{entry}: new jit entry not pinned in hlo_baseline.json — "
                "justify it with --update-baseline (and a reason)"
                + version_note
            )
            continue
        for metric in COUNT_METRICS:
            want = pinned.get("counts", {}).get(metric)
            got = recorded.get(metric)
            if want is None or got is None:
                continue
            if got != want:
                findings.append(
                    f"{entry}.{metric}: {got} vs baseline {want} "
                    f"(exact-match metric; a kernel was "
                    f"{'added' if got > want else 'removed'})" + version_note
                )
        rel = float(baseline.get("tolerance", {}).get("cost_rel", 0.10))
        for metric in ("flops", "bytes_accessed"):
            want = pinned.get(metric)
            got = recorded.get(metric)
            if not want or got is None:
                continue
            if abs(got - want) > rel * abs(want):
                findings.append(
                    f"{entry}.{metric}: {got:.0f} vs baseline {want:.0f} "
                    f"(outside ±{rel:.0%} tolerance)" + version_note
                )
    for entry in sorted(entries):
        if entry not in census:
            findings.append(
                f"baseline entry {entry!r} was not exercised by the census "
                "scenario (stale — remove it, or fix the scenario)"
            )
    return findings


def update_baseline(census: Dict[str, Dict], path: Optional[str] = None) -> str:
    from bcg_tpu.obs.hlo import COUNT_METRICS

    import jax

    path = path or baseline_path()
    prior = load_baseline(path) or {}
    prior_entries = prior.get("entries", {})
    entries = {}
    for entry, recorded in sorted(census.items()):
        if "error" in recorded:
            continue
        entries[entry] = {
            "reason": prior_entries.get(entry, {}).get(
                "reason",
                "pinned by scripts/hlo_census.py --update-baseline; "
                "justify intentional kernel-count changes here",
            ),
            "counts": {
                m: recorded[m] for m in COUNT_METRICS if m in recorded
            },
        }
        for metric in ("flops", "bytes_accessed"):
            if metric in recorded:
                entries[entry][metric] = recorded[metric]
    data = {
        "_comment": (
            "HLO kernel-census baseline (scripts/hlo_census.py). Count "
            "metrics are exact-match on this backend: a change that adds "
            "a kernel to any pinned jit entry fails tier-1 "
            "(tests/test_hlo_census.py) until re-justified here via "
            "--update-baseline. flops/bytes_accessed carry a relative "
            "tolerance (tolerance.cost_rel)."
        ),
        "backend": jax.default_backend(),
        "jax_version": jax.__version__,
        "tolerance": prior.get("tolerance", {"cost_rel": 0.10}),
        "entries": entries,
    }
    with open(path, "w") as f:
        json.dump(data, f, indent=2)
        f.write("\n")
    return path


# ------------------------------------------------------------------ render
def render_table(census: Dict[str, Dict]) -> str:
    cols = ("fusions", "custom_calls", "collectives", "scatters", "gathers",
            "step_ops", "step_fusions", "total_ops")
    lines = []
    name_w = max([len("entry")] + [len(e) for e in census])
    header = f"{'entry':<{name_w}}  " + "  ".join(f"{c:>12}" for c in cols) \
        + f"  {'flops':>14}  {'bytes_acc':>14}"
    lines.append(header)
    for entry, rec in sorted(census.items()):
        if "error" in rec:
            lines.append(f"{entry:<{name_w}}  census failed: {rec['error']}")
            continue
        row = f"{entry:<{name_w}}  " + "  ".join(
            f"{rec.get(c, '-'):>12}" for c in cols
        )
        row += f"  {rec.get('flops', 0):>14.0f}  {rec.get('bytes_accessed', 0):>14.0f}"
        lines.append(row)
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Lowered-HLO kernel census per engine jit entry "
        "(hermetic CPU scenario)."
    )
    parser.add_argument("--check", action="store_true",
                        help="compare against hlo_baseline.json; rc 2 on drift")
    parser.add_argument("--update-baseline", action="store_true",
                        help="regenerate hlo_baseline.json (keeps reasons)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="print the census as JSON")
    parser.add_argument("--arms", default=",".join(ARMS),
                        help=f"decode-loop families to exercise ({','.join(ARMS)})")
    args = parser.parse_args(argv)

    arms = tuple(a for a in args.arms.split(",") if a)
    bad = [a for a in arms if a not in ARMS]
    if bad:
        print(f"unknown arms {bad}; known: {ARMS}", file=sys.stderr)
        return 1
    census = run_scenario(arms)
    if args.as_json:
        print(json.dumps(census, indent=2, sort_keys=True))
    else:
        print(render_table(census))
    if args.update_baseline:
        path = update_baseline(census)
        print(f"baseline written: {path}", file=sys.stderr)
        return 0
    if args.check:
        findings = check_drift(census, load_baseline())
        for f in findings:
            print(f"DRIFT: {f}", file=sys.stderr)
        if findings:
            return 2
        print("hlo census matches baseline", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
