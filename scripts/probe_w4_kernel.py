#!/usr/bin/env python
"""Lower and validate the W4A16 Pallas kernel on the attached TPU.

The int4 decode kernel (ops/w4_matmul.py) is interpret-mode tested on
CPU, but Mosaic lowering rules differ on real hardware (round-2/3
lessons: scale blockspecs, (1,1,1) VMEM blocks, bool SMEM).  This probe
runs the kernel at decode shapes — tiny, bench-1b, and 14B w_down
dims — and checks each against the XLA dequant fallback, so a lowering
problem surfaces as a named failure here instead of a crash deep inside
the queued 14B bench.

Prints one line per case and "w4-kernel-probe OK" when all pass.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from bcg_tpu.models.quantize import dequantize_int4, quantize_weight_int4
from bcg_tpu.ops.w4_matmul import w4a16_matmul, w4a16_supported


CASES = [
    # (rows, in_dim, out_dim) at decode row counts
    ("tiny", 8, 256, 512),
    ("1b-ffn", 10, 2048, 6144),
    ("14b-qkv", 10, 5120, 8192),
    ("14b-wdown", 10, 17408, 5120),
    ("14b-wdown-retry-rows", 160, 17408, 5120),
]


def main() -> None:
    backend = jax.default_backend()
    print("backend:", backend)
    if backend != "tpu":
        # Off-TPU the kernel falls back to the very XLA path used as the
        # reference below — "OK" would be vacuous and would stamp the
        # watcher step without ever lowering the kernel.  "unavailable"
        # keeps the watcher's availability triage retrying (a tunnel can
        # die between the watcher's probe and this step, silently
        # falling JAX back to CPU) instead of burning failure strikes.
        print("w4-kernel-probe FAILED: accelerator unavailable "
              "(backend is not tpu; nothing validated)")
        raise SystemExit(1)
    rng = np.random.default_rng(0)
    ok = True
    for name, m, din, dout in CASES:
        w = jnp.asarray(rng.standard_normal((din, dout)) * 0.02, jnp.bfloat16)
        qw = quantize_weight_int4(w)
        x = jnp.asarray(rng.standard_normal((m, din)) * 0.5, jnp.bfloat16)
        # The kernel silently falls back to the XLA dequant path (the
        # very reference below) for unsupported shapes — "OK" would
        # then be vacuous, so unsupported cases are hard failures here.
        if not w4a16_supported(
            (m, din), qw["q4"].shape, qw["gscale"].shape
        ):
            ok = False
            print(f"  {name:<22s} UNSUPPORTED shape (kernel would fall "
                  f"back; probe would compare XLA to XLA)")
            continue
        try:
            got = np.asarray(w4a16_matmul(x, qw["q4"], qw["gscale"]))
            want = np.asarray(
                (x.astype(jnp.bfloat16) @ dequantize_int4(qw)).astype(jnp.float32)
            )
            err = float(np.max(np.abs(got - want)))
            rel = err / (float(np.max(np.abs(want))) + 1e-9)
            # `not (rel < tol)` so NaN (from a miscompile) fails too.
            good = rel < 2e-2
            status = "OK" if good else f"MISMATCH rel={rel:.3e}"
            if not good:
                ok = False
            print(f"  {name:<22s} [{m}x{din}]@[{din}x{dout}]  max|d|={err:.4f}  {status}")
        except Exception as exc:  # noqa: BLE001 — a probe reports, not crashes
            ok = False
            print(f"  {name:<22s} FAILED: {type(exc).__name__}: {str(exc)[:200]}")
    print("w4-kernel-probe OK" if ok else "w4-kernel-probe FAILED")
    raise SystemExit(0 if ok else 1)


if __name__ == "__main__":
    main()
