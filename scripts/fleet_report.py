#!/usr/bin/env python
"""Fleet report from per-process metric shards (BCG_TPU_METRICS_SHARD_DIR).

``python scripts/fleet_report.py SHARD_DIR_OR_FILES... [--watch]``

Each process of a fleet run appends cumulative typed registry snapshots
(counters/gauges/histograms + identity + heartbeat) to
``shard-<run_id>-<process>.jsonl`` (``bcg_tpu/obs/fleet.py``).  This
script merges the NEWEST record per shard into fleet tables, grouped by
run id:

* **counters** — summed across ranks, with a per-host breakdown and
  cross-rank skew columns (the p95 rank's value vs the median rank's —
  a hot or cold rank shows as skew, not as a mysteriously-off mean);
* **histograms** — merged bucket-wise (fixed declared bounds make two
  histograms addable), with fleet-level p50/p95/p99 derived from the
  merged buckets exactly like the in-process registry derives them;
* **gauges** — point-in-time per-rank values (a gauge has no meaningful
  cross-rank sum), listed rank by rank;
* **liveness** (``--watch``) — per-rank watermark + heartbeat age, and
  straggler flags: a rank lagging the fleet median watermark by the
  ``--straggler-factor`` (or whose heartbeat is older than factor x its
  flush period) is named, and the exit code is 3 — so a sweep driver
  can poll this in a loop and alarm.

Self-contained — no bcg_tpu import — so shards copied off a hundred
sweep workers aggregate anywhere (the trace_report/consensus_report
contract).  The straggler rule and the bucket-quantile interpolation
mirror ``bcg_tpu/obs`` by value; ``tests/test_fleet.py`` holds the
mirrors to the same verdicts.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
from collections import defaultdict
from typing import Any, Dict, List, Optional, Sequence, Tuple

# The shard schema this report understands (mirrors
# bcg_tpu.obs.fleet.SHARD_SCHEMA_VERSION — by value, not import).
KNOWN_SHARD_SCHEMA_VERSIONS = (1,)


# ------------------------------------------------------------------ loading
def read_last_record(path: str) -> Optional[Dict[str, Any]]:
    """Newest parseable JSONL record of one shard file (shards are
    cumulative snapshots — the last line is the rank's current state; a
    line truncated mid-write falls back to the one before it)."""
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - 262144))
            tail = f.read().decode("utf-8", errors="replace")
    except OSError:
        return None
    for line in reversed(tail.strip().splitlines()):
        line = line.strip()
        if not line:
            continue
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    return None


def shard_files(paths: Sequence[str], problems: List[str]) -> List[str]:
    """Expand directories to their shard-*.jsonl members."""
    out: List[str] = []
    for path in paths:
        if os.path.isdir(path):
            members = sorted(
                os.path.join(path, name)
                for name in os.listdir(path)
                if name.startswith("shard-") and name.endswith(".jsonl")
            )
            if not members:
                problems.append(f"{path}: no shard-*.jsonl files")
            out.extend(members)
        else:
            out.append(path)
    return out


def load_shards(paths: Sequence[str],
                problems: List[str]) -> List[Dict[str, Any]]:
    """Newest record per shard file, schema-checked."""
    records = []
    for path in shard_files(paths, problems):
        rec = read_last_record(path)
        if rec is None:
            problems.append(f"{path}: no parseable shard record")
            continue
        version = rec.get("schema_version")
        if version not in KNOWN_SHARD_SCHEMA_VERSIONS:
            problems.append(
                f"{path}: unknown shard schema_version {version!r} "
                f"(this report understands {KNOWN_SHARD_SCHEMA_VERSIONS})"
            )
            continue
        rec["_path"] = path
        records.append(rec)
    return records


def group_by_run(records: List[Dict[str, Any]]) -> Dict[str, List[Dict]]:
    runs: Dict[str, List[Dict]] = defaultdict(list)
    for rec in records:
        ident = rec.get("identity") or {}
        runs[str(ident.get("run_id", "(unknown run)"))].append(rec)
    for group in runs.values():
        group.sort(
            key=lambda r: (r.get("identity") or {}).get("process_index", 0)
        )
    return dict(sorted(runs.items()))


# ------------------------------------------------------------------ merging
def _rank_label(rec: Dict[str, Any]) -> str:
    ident = rec.get("identity") or {}
    return f"{ident.get('process_index', '?')}@{ident.get('host', '?')}"


def _p95(values: List[float]) -> float:
    ordered = sorted(values)
    if not ordered:
        return 0.0
    idx = min(len(ordered) - 1, int(round(0.95 * (len(ordered) - 1))))
    return ordered[idx]


def merge_counters(records: List[Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
    """Per counter name: fleet total (sum), per-rank and per-host
    breakdowns, and the cross-rank skew pair (p95 rank vs median rank —
    absent ranks count 0: a rank that never touched a counter IS part
    of the fleet distribution)."""
    names = sorted({
        name for rec in records for name in (rec.get("counters") or {})
    })
    out: Dict[str, Dict[str, Any]] = {}
    for name in names:
        per_rank: Dict[str, float] = {}
        per_host: Dict[str, float] = defaultdict(float)
        for rec in records:
            value = float((rec.get("counters") or {}).get(name, 0))
            per_rank[_rank_label(rec)] = value
            host = (rec.get("identity") or {}).get("host", "?")
            per_host[str(host)] += value
        values = list(per_rank.values())
        med = float(statistics.median(values)) if values else 0.0
        p95 = _p95(values)
        out[name] = {
            "total": sum(values),
            "per_rank": per_rank,
            "per_host": dict(sorted(per_host.items())),
            "median_rank": med,
            "p95_rank": p95,
            "skew": round(p95 / med, 3) if med else None,
        }
    return out


def merge_gauges(records: List[Dict[str, Any]]) -> Dict[str, Dict[str, float]]:
    """Per gauge name: the per-rank values (gauges are point-in-time —
    summing them across ranks would fabricate a meaningless number)."""
    names = sorted({
        name for rec in records for name in (rec.get("gauges") or {})
    })
    return {
        name: {
            _rank_label(rec): float(rec["gauges"][name])
            for rec in records
            if name in (rec.get("gauges") or {})
        }
        for name in names
    }


def merge_histograms(
    records: List[Dict[str, Any]], problems: List[str]
) -> Dict[str, Dict[str, Any]]:
    """Bucket-wise merge: per histogram name, the ranks' cumulative
    bucket counts add bound-for-bound (declared bounds must agree —
    mismatched bounds are reported and the offending rank skipped, not
    silently blended into a wrong distribution)."""
    out: Dict[str, Dict[str, Any]] = {}
    for rec in records:
        for name, hist in (rec.get("histograms") or {}).items():
            bounds = tuple(float(b) for b, _ in hist.get("buckets", []))
            merged = out.get(name)
            if merged is None:
                out[name] = {
                    "bounds": bounds,
                    "cumulative": [float(c) for _, c in hist["buckets"]],
                    "sum": float(hist.get("sum", 0.0)),
                    "count": int(hist.get("count", 0)),
                }
                continue
            if bounds != merged["bounds"]:
                problems.append(
                    f"histogram {name!r}: rank {_rank_label(rec)} declares "
                    f"bounds {bounds}, fleet has {merged['bounds']} — rank "
                    "skipped"
                )
                continue
            merged["cumulative"] = [
                a + float(c)
                for a, (_, c) in zip(merged["cumulative"], hist["buckets"])
            ]
            merged["sum"] += float(hist.get("sum", 0.0))
            merged["count"] += int(hist.get("count", 0))
    return out


def quantile_from_cumulative(bounds: Sequence[float],
                             cumulative: Sequence[float],
                             count: int, q: float) -> float:
    """Prometheus histogram_quantile over cumulative finite-bound
    counts + total (mirrors bcg_tpu.obs.counters.quantile_from_counts
    by value: linear interpolation inside the target bucket, the
    highest finite bound for the overflow bucket)."""
    if count <= 0:
        return 0.0
    target = q * count
    prev_bound = 0.0
    prev_cum = 0.0
    for bound, cum in zip(bounds, cumulative):
        in_bucket = cum - prev_cum
        if cum >= target and in_bucket > 0:
            frac = (target - prev_cum) / in_bucket
            return prev_bound + (float(bound) - prev_bound) * max(
                0.0, min(1.0, frac)
            )
        prev_bound = float(bound)
        prev_cum = cum
    return float(bounds[-1]) if bounds else 0.0


def histogram_quantiles(merged: Dict[str, Any],
                        qs: Sequence[float] = (0.5, 0.95, 0.99)
                        ) -> Dict[str, float]:
    return {
        f"p{int(round(q * 100))}": quantile_from_cumulative(
            merged["bounds"], merged["cumulative"], merged["count"], q
        )
        for q in qs
    }


# ------------------------------------------------------------ liveness/watch
def detect_stragglers(
    records: List[Dict[str, Any]],
    factor: float,
    now_ms: Optional[float] = None,
) -> List[Dict[str, Any]]:
    """Ranks lagging the fleet (mirrors
    bcg_tpu.obs.fleet.detect_stragglers by value): watermark under
    median/factor, or heartbeat older than factor x the rank's flush
    period relative to the freshest rank (offline) / now (live).
    ``factor <= 0`` disables; fewer than 2 ranks have no median to
    lag."""
    if factor <= 0 or len(records) < 2:
        return []
    gauges = [r.get("gauges") or {} for r in records]
    watermarks = [float(g.get("fleet.watermark", 0)) for g in gauges]
    heartbeats = [
        float(r.get("heartbeat_ms") or g.get("fleet.heartbeat_ms", 0))
        for r, g in zip(records, gauges)
    ]
    med_watermark = statistics.median(watermarks)
    ref_ms = now_ms if now_ms is not None else max(heartbeats, default=0.0)
    out = []
    for rec, w, hb in zip(records, watermarks, heartbeats):
        reasons = []
        if med_watermark > 0 and w * factor < med_watermark:
            reasons.append("watermark")
        flush_ms = float(rec.get("flush_ms") or 1000.0)
        if hb > 0 and (ref_ms - hb) > factor * flush_ms:
            reasons.append("heartbeat")
        if reasons:
            ident = rec.get("identity") or {}
            out.append({
                "process_index": ident.get("process_index"),
                "host": ident.get("host"),
                "reasons": reasons,
                "watermark": w,
                "median_watermark": med_watermark,
                "heartbeat_age_ms": round(ref_ms - hb, 1) if hb else None,
            })
    return out


# ---------------------------------------------------------------- rendering
def render_run(run: str, records: List[Dict[str, Any]],
               problems: List[str]) -> str:
    counters = merge_counters(records)
    gauges = merge_gauges(records)
    hists = merge_histograms(records, problems)
    hosts = sorted({
        str((r.get("identity") or {}).get("host", "?")) for r in records
    })
    lines = [
        f"== run {run}: {len(records)} rank(s) on {len(hosts)} host(s) "
        f"({', '.join(hosts)}) =="
    ]
    if counters:
        width = max(len(n) for n in counters)
        lines.append(
            f"{'counter':<{width}}  {'fleet_total':>12}  {'median_rank':>11}  "
            f"{'p95_rank':>9}  {'skew':>6}  per_host"
        )
        for name, row in counters.items():
            skew = f"{row['skew']:.2f}" if row["skew"] is not None else "-"
            hosts_s = " ".join(
                f"{host}={value:g}" for host, value in row["per_host"].items()
            )
            lines.append(
                f"{name:<{width}}  {row['total']:>12g}  "
                f"{row['median_rank']:>11g}  {row['p95_rank']:>9g}  "
                f"{skew:>6}  {hosts_s}"
            )
    if hists:
        lines.append("")
        lines.append("-- merged histograms (bucket-wise across ranks) --")
        width = max(len(n) for n in hists)
        lines.append(
            f"{'histogram':<{width}}  {'count':>8}  {'p50':>9}  {'p95':>9}  "
            f"{'p99':>9}"
        )
        for name, merged in sorted(hists.items()):
            q = histogram_quantiles(merged)
            lines.append(
                f"{name:<{width}}  {merged['count']:>8}  {q['p50']:>9.2f}  "
                f"{q['p95']:>9.2f}  {q['p99']:>9.2f}"
            )
    fleet_gauges = {
        n: v for n, v in gauges.items()
        if n.startswith("fleet.") or len(records) > 1
    }
    if fleet_gauges:
        lines.append("")
        lines.append("-- gauges (per-rank; point-in-time, never summed) --")
        width = max(len(n) for n in fleet_gauges)
        for name, per_rank in fleet_gauges.items():
            ranks_s = " ".join(
                f"{rank}={value:g}" for rank, value in per_rank.items()
            )
            lines.append(f"{name:<{width}}  {ranks_s}")
    return "\n".join(lines)


def render_watch(run: str, records: List[Dict[str, Any]],
                 factor: float) -> Tuple[str, bool]:
    """Liveness table + straggler flags for one run; returns the text
    and whether any rank is flagged."""
    flagged = detect_stragglers(records, factor)
    flagged_by_proc = {f["process_index"]: f for f in flagged}
    heartbeats = [
        float(r.get("heartbeat_ms")
              or (r.get("gauges") or {}).get("fleet.heartbeat_ms", 0))
        for r in records
    ]
    ref_ms = max(heartbeats, default=0.0)
    lines = [f"== run {run}: liveness ({len(records)} rank(s), "
             f"straggler factor {factor:g}) =="]
    lines.append(f"{'rank':<24}  {'watermark':>9}  {'hb_age_ms':>10}  "
                 f"{'alerts':<20}  status")
    for rec, hb in zip(records, heartbeats):
        ident = rec.get("identity") or {}
        proc = ident.get("process_index")
        gauges = rec.get("gauges") or {}
        watermark = float(gauges.get("fleet.watermark", 0))
        age = f"{ref_ms - hb:.0f}" if hb else "-"
        # Firing alerts ride the shard plane as alert.firing.<rule>
        # gauges (BCG_TPU_ALERTS; absent rank-side = '-', present but
        # all zero = 'none').
        firing = sorted(
            n[len("alert.firing."):] for n, v in gauges.items()
            if n.startswith("alert.firing.") and v
        )
        if firing:
            alerts = ",".join(firing)
        else:
            alerts = ("none" if any(n.startswith("alert.firing.")
                                    for n in gauges) else "-")
        hit = flagged_by_proc.get(proc)
        status = (
            f"STRAGGLER ({'+'.join(hit['reasons'])})" if hit else "ok"
        )
        lines.append(
            f"{_rank_label(rec):<24}  {watermark:>9g}  {age:>10}  "
            f"{alerts:<20}  {status}"
        )
    return "\n".join(lines), bool(flagged)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Merge per-process metric shards "
        "(BCG_TPU_METRICS_SHARD_DIR) into fleet tables with per-host "
        "breakdowns, cross-rank skew, and straggler flags."
    )
    parser.add_argument("shards", nargs="+",
                        help="shard dirs and/or shard-*.jsonl paths")
    parser.add_argument("--watch", action="store_true",
                        help="liveness pass: per-rank watermark + "
                        "heartbeat age; exit 3 when any rank is flagged "
                        "as a straggler")
    parser.add_argument("--straggler-factor", type=float, default=3.0,
                        help="lag factor for --watch flags (default 3)")
    args = parser.parse_args(argv)
    problems: List[str] = []
    records = load_shards(args.shards, problems)
    if not records:
        print("fleet_report: no shard records found", file=sys.stderr)
        for problem in problems:
            print(f"WARNING: {problem}", file=sys.stderr)
        return 1
    runs = group_by_run(records)
    any_stragglers = False
    blocks = []
    for run, group in runs.items():
        if args.watch:
            text, flagged = render_watch(run, group, args.straggler_factor)
            any_stragglers = any_stragglers or flagged
            blocks.append(text)
        else:
            blocks.append(render_run(run, group, problems))
    print("\n\n".join(blocks))
    for problem in problems:
        print(f"WARNING: {problem}", file=sys.stderr)
    return 3 if (args.watch and any_stragglers) else 0


if __name__ == "__main__":
    sys.exit(main())
