#!/bin/bash
# Hardware-recovery watcher for the round-3 validation queue.
#
# The axon-tunneled TPU comes and goes (see BENCH_NOTES outage
# timelines).  This script probes the chip with a real (non-toy)
# compile; when a probe succeeds it drains the queued benches /
# parity sweeps one at a time, stamping <name>.done in $OUT so a
# restarted watcher resumes where it left off.  A step whose output
# looks like an availability failure is retried on the next healthy
# window; a step that fails twice for any other reason is stamped
# <name>.skip and reported in the log instead of wedging the queue.
set -u
cd /root/repo
OUT=results/hw_r3b
declare -A TMO
LOG=$OUT/watcher.log
mkdir -p "$OUT"

log() { echo "$(date -u +%H:%M:%S) $*" >> "$LOG"; }

probe() {
  timeout 240 python - <<'EOF' >/dev/null 2>&1
import jax, jax.numpy as jnp
jax.devices()
x = jnp.ones((1024, 1024), jnp.bfloat16)
for _ in range(3):
    x = (x @ x) * 0.001
x.block_until_ready()
EOF
}

# run_step <name> <timeout_s> <success_grep> <cmd...>
run_step() {
  local name=$1 tmo=$2 ok_pat=$3; shift 3
  [ -e "$OUT/$name.done" ] && return 0
  [ -e "$OUT/$name.skip" ] && return 0
  log "START $name"
  timeout "$tmo" "$@" > "$OUT/$name.json" 2> "$OUT/$name.log"
  local rc=$?
  if [ $rc -eq 0 ] && grep -q "$ok_pat" "$OUT/$name.json" \
      && ! grep -qi '"error"' "$OUT/$name.json"; then
    touch "$OUT/$name.done"
    log "DONE $name: $(tail -c 300 "$OUT/$name.json" | tr '\n' ' ')"
    return 0
  fi
  # Availability failure (attach error, tunnel death): leave un-stamped
  # and signal the caller to go back to probing.
  if grep -qiE "unavailable|attach|connection refused|response body closed" \
      "$OUT/$name.json" "$OUT/$name.log" 2>/dev/null; then
    log "UNAVAIL $name rc=$rc — back to probing"
    return 2
  fi
  # A timeout can be a mid-step hang (chip died) OR a legitimately slow
  # step on healthy hardware.  Disambiguate with an immediate re-probe:
  # a dead chip means an outage timeout (retry forever, like UNAVAIL);
  # a healthy probe means the step itself is too slow — bound those so
  # one deterministically-slow step can't wedge the steps behind it.
  if [ $rc -eq 124 ]; then
    if ! probe; then
      log "TIMEOUT $name during outage (probe fails) — back to probing"
      return 2
    fi
    # In-memory counter (not a stamp file): an outage that ends just
    # before the re-probe would be misattributed as a healthy-hardware
    # timeout, and persisting that across watcher restarts could
    # permanently skip a healthy step after a few flappy windows.
    TMO[$name]=$(( ${TMO[$name]:-0} + 1 ))
    local tmos=${TMO[$name]}
    log "TIMEOUT $name on healthy hardware attempt=$tmos"
    if [ "$tmos" -ge 3 ]; then
      touch "$OUT/$name.skip"
      log "SKIP $name after $tmos healthy-hardware timeouts"
      return 0  # settled (like .done): drain continues to the next step
    fi
    return 3  # healthy-hardware timeout: re-probe, but DON'T reset TMO
  fi
  local fails=$(( $(cat "$OUT/$name.fails" 2>/dev/null || echo 0) + 1 ))
  echo "$fails" > "$OUT/$name.fails"
  log "FAIL $name rc=$rc attempt=$fails: $(tail -c 300 "$OUT/$name.log" | tr '\n' ' ')"
  if [ "$fails" -ge 2 ]; then
    touch "$OUT/$name.skip"
    log "SKIP $name after $fails failures"
    return 0  # settled: drain continues to the next step
  fi
  return 1
}

drain() {
  run_step bench_default 1500 '"value"' \
    env BENCH_ROUNDS=3 python bench.py || return $?
  run_step bench_int8kv 1500 '"value"' \
    env BENCH_ROUNDS=3 BENCH_KV_DTYPE=int8 python bench.py || return $?
  run_step bench_hf1b 1800 '"value"' \
    env BENCH_ROUNDS=3 BENCH_MODEL=bcg-hf/bench-1b python bench.py || return $?
  run_step bench_conc2 1800 '"value"' \
    env BENCH_ROUNDS=3 BENCH_CONCURRENCY=2 python bench.py || return $?
  run_step art_convert 1200 'saved int8 artifact' \
    env PYTHONPATH=/root/repo python -m bcg_tpu.models.artifact \
      --model bcg-hf/bench-1b --mode int8 \
      --out checkpoints_q/bcg-hf--bench-1b || return $?
  # Gated on the artifact actually existing: without it the env dir is
  # skipped by checkpoint discovery and the bench would silently
  # re-measure the plain HF boot path and stamp a bogus .done.
  if [ -e "$OUT/art_convert.done" ] \
      && [ -f checkpoints_q/bcg-hf--bench-1b/bcg_tpu_quantized.json ]; then
    run_step bench_artifact 1800 '"value"' \
      env BENCH_ROUNDS=3 BENCH_MODEL=bcg-hf/bench-1b \
        BCG_TPU_CHECKPOINT_DIR=checkpoints_q python bench.py || return $?
  elif [ -e "$OUT/art_convert.skip" ] && [ ! -e "$OUT/bench_artifact.skip" ]; then
    touch "$OUT/bench_artifact.skip"
    log "SKIP bench_artifact: artifact conversion was skipped"
  fi
  run_step bench_bf16w 1500 '"value"' \
    env BENCH_ROUNDS=3 BENCH_QUANTIZATION=none python bench.py || return $?
  run_step bench_finesuffix 1500 '"value"' \
    env BENCH_ROUNDS=3 BCG_TPU_FINE_SUFFIX=1 python bench.py || return $?
  run_step bench_w8a16 1500 '"value"' \
    env BENCH_ROUNDS=3 BCG_TPU_W8A16_PREFILL=512 python bench.py || return $?
  run_step mb_prefill 2400 'rmsnorm' \
    env PYTHONPATH=/root/repo python scripts/microbench_prefill.py || return $?
  run_step mb_decode 2400 'in-loop' \
    env PYTHONPATH=/root/repo python scripts/microbench_decode_attention.py || return $?
  run_step bench_8b 3600 '"value"' \
    env BENCH_ROUNDS=3 BENCH_MODEL=bcg-tpu/bench-8b python bench.py || return $?
  run_step bench_14b 5400 '"value"' \
    env BENCH_ROUNDS=2 BENCH_MODEL=bcg-tpu/bench-14b python bench.py || return $?
  local p
  for p in q1-baseline q1-full q2; do
    run_step "parity_$p" 5400 '"aggregate"' \
      python -m bcg_tpu.experiments "$p" --backend jax \
        --model bcg-tpu/bench-1b --runs 10 --rounds 8 \
        --concurrency 2 --seed 100 || return $?
  done
  return 0
}

all_done() {
  local s
  for s in bench_default bench_int8kv bench_hf1b bench_conc2 \
           art_convert bench_artifact bench_bf16w \
           bench_finesuffix bench_w8a16 mb_prefill mb_decode \
           bench_8b bench_14b \
           parity_q1-baseline parity_q1-full parity_q2; do
    [ -e "$OUT/$s.done" ] || [ -e "$OUT/$s.skip" ] || return 1
  done
  return 0
}

log "watcher started (pid $$)"
while true; do
  if all_done; then log "queue fully drained — exiting"; exit 0; fi
  if probe; then
    log "probe OK — draining queue"
    drain
    rc=$?
    [ $rc -eq 0 ] && continue
    log "drain interrupted rc=$rc"
    # rc=2 means an outage was observed mid-drain (UNAVAIL or a
    # timeout whose re-probe failed): same invalidation as a failed
    # top-level probe — healthy-timeout attribution starts over.
    # rc=3 (healthy-hardware timeout) keeps its count: wiping it here
    # would make the 3-strike skip unreachable.
    [ $rc -eq 2 ] && TMO=()
  else
    log "probe failed (tpu not ready)"
    # An observed outage invalidates the healthy-timeout attribution:
    # any step timeout counted during a flappy window may have been the
    # outage's fault, so start the 3-strike count over.
    TMO=()
  fi
  sleep 300
done
