#!/bin/bash
# Hardware-recovery watcher for the hardware validation queue.
#
# The axon-tunneled TPU comes and goes (see BENCH_NOTES outage
# timelines).  This script probes the chip with a real (non-toy)
# compile; when a probe succeeds it drains the queued benches /
# parity sweeps one at a time, stamping <name>.done in $OUT so a
# restarted watcher resumes where it left off.  A step whose output
# looks like an availability failure is retried on the next healthy
# window; a step that fails twice for any other reason (or times out
# 3x on provably-healthy hardware) is stamped <name>.skip and reported
# in the log instead of wedging the queue.
set -u
cd /root/repo
OUT=${HW_WATCHER_OUT:-results/hw_r5}
declare -A TMO
LOG=$OUT/watcher.log
mkdir -p "$OUT"

# Single source of truth for the queue: drain() runs these in order and
# all_done() checks the same list, so the two can never drift.
# Round-4 order follows the verdict's priorities: a recorded default
# number first, then the 8B/14B capability proofs (with the kernel
# probes they depend on), then the prefill-MFU attack, then the smaller
# A/Bs, with the long parity sweeps last — a short healthy window must
# not be spent on minor A/Bs while the flagship claims starve.
# Round-5 reorder (post-flagship): default/8B/kernel-probe numbers are
# BANKED (.done), so the next healthy window goes to the remaining
# verdict asks in priority order — the kernel-ON int8 arm, the repaired
# W4 probe, the 14B capacity number, the trained-BPE fixture bench, then
# ONE hardware parity distribution (q2, the headline config) ahead of
# the attribution microbenches and minor A/Bs; the two remaining parity
# sweeps close the queue.
STEPS="bench_default int8_probe bench_int8kv bench_8b w4_probe flash_probe bench_14b \
bench_hf1b parity_q2 mb_prefill bench_w8a16 bench_8b_unroll bench_bf16w \
bench_finesuffix bench_conc2 art_convert bench_artifact mb_decode \
bench_14b_kernel parity_q1-baseline parity_q1-full"

log() { echo "$(date -u +%H:%M:%S) $*" >> "$LOG"; }

probe() {
  timeout 240 python - <<'EOF' >/dev/null 2>&1
import jax, jax.numpy as jnp
jax.devices()
x = jnp.ones((1024, 1024), jnp.bfloat16)
for _ in range(3):
    x = (x @ x) * 0.001
x.block_until_ready()
EOF
}

# step_spec <name>: sets TMOS (timeout s), PAT (success grep), CMD (argv).
step_spec() {
  # If the int8 decode kernels failed their hardware probe, every
  # int8-KV bench (bench_int8kv, bench_8b, bench_14b) degrades to the
  # dequant fallback instead of crashing on the same lowering bug.
  INT8_FALLBACK=()
  if [ -e "$OUT/int8_probe.skip" ]; then
    INT8_FALLBACK=(BCG_TPU_DISABLE_INT8_DECODE_KERNEL=1)
  fi
  # W4 kernel fallback is shared by bench_14b and bench_14b_kernel.
  W4_FALLBACK=()
  if [ -e "$OUT/w4_probe.skip" ]; then
    W4_FALLBACK=(BCG_TPU_DISABLE_W4_KERNEL=1)
  fi
  case $1 in
    bench_default)
      # 45 min: round-4 code changes invalidate the persistent XLA cache,
      # so the first post-outage bench repays every compile through the
      # (possibly degraded) remote-compile helper — 25 min was too tight.
      TMOS=2700; PAT='"value"'
      CMD=(env BENCH_ROUNDS=3 python bench.py);;
    int8_probe)
      TMOS=1200; PAT='int8-decode-probe OK'
      CMD=(env PYTHONPATH=/root/repo${PYTHONPATH:+:$PYTHONPATH} python scripts/probe_int8_decode.py);;
    bench_int8kv)
      TMOS=1500; PAT='"value"'
      CMD=(env BENCH_ROUNDS=3 BENCH_KV_DTYPE=int8
           ${INT8_FALLBACK[@]+"${INT8_FALLBACK[@]}"} python bench.py);;
    bench_hf1b)
      # 40 min: post-outage cold cache + HF tokenizer/token-DFA build
      # on top of the normal compile bill (default took 8.5 min warmless
      # this morning; the HF arm adds the trained-BPE table builds).
      TMOS=2400; PAT='"value"'
      CMD=(env BENCH_ROUNDS=3 BENCH_MODEL=bcg-hf/bench-1b python bench.py);;
    bench_conc2)
      TMOS=1800; PAT='"value"'
      CMD=(env BENCH_ROUNDS=3 BENCH_CONCURRENCY=2 python bench.py);;
    art_convert)
      TMOS=1200; PAT='saved int8 artifact'
      CMD=(env PYTHONPATH=/root/repo${PYTHONPATH:+:$PYTHONPATH} python -m bcg_tpu.models.artifact
           --model bcg-hf/bench-1b --mode int8
           --out checkpoints_q/bcg-hf--bench-1b);;
    bench_artifact)
      TMOS=1800; PAT='"value"'
      CMD=(env BENCH_ROUNDS=3 BENCH_MODEL=bcg-hf/bench-1b
           BCG_TPU_CHECKPOINT_DIR=checkpoints_q python bench.py);;
    bench_bf16w)
      TMOS=1500; PAT='"value"'
      CMD=(env BENCH_ROUNDS=3 BENCH_QUANTIZATION=none python bench.py);;
    bench_finesuffix)
      TMOS=1500; PAT='"value"'
      CMD=(env BENCH_ROUNDS=3 BCG_TPU_FINE_SUFFIX=1 python bench.py);;
    bench_w8a16)
      TMOS=1500; PAT='"value"'
      CMD=(env BENCH_ROUNDS=3 BCG_TPU_W8A16_PREFILL=512 python bench.py);;
    mb_prefill)
      TMOS=2400; PAT='rmsnorm'
      CMD=(env PYTHONPATH=/root/repo${PYTHONPATH:+:$PYTHONPATH} python scripts/microbench_prefill.py);;
    mb_decode)
      TMOS=2400; PAT='in-loop'
      CMD=(env PYTHONPATH=/root/repo${PYTHONPATH:+:$PYTHONPATH} python scripts/microbench_decode_attention.py);;
    bench_8b)
      TMOS=4500; PAT='"value"'
      CMD=(env BENCH_ROUNDS=3 BENCH_MODEL=bcg-tpu/bench-8b
           ${INT8_FALLBACK[@]+"${INT8_FALLBACK[@]}"} python bench.py);;
    bench_8b_unroll)
      # Decode-overlap A/B: 8B decode measured 43% of the HBM roof vs
      # 87.5% at 1B; scan-over-layers (forced ON for the large class to
      # make the remote compile tractable) is the prime suspect — the
      # unrolled form keeps better cache-update aliasing in the decode
      # loop.  With the persistent compile cache warm from bench_8b the
      # unrolled compile may now be affordable.
      TMOS=4500; PAT='"value"'
      CMD=(env BENCH_ROUNDS=3 BENCH_MODEL=bcg-tpu/bench-8b
           BENCH_SCAN_LAYERS=0
           ${INT8_FALLBACK[@]+"${INT8_FALLBACK[@]}"} python bench.py);;
    flash_probe)
      TMOS=1500; PAT='flash-prefill-probe OK'
      CMD=(env PYTHONPATH=/root/repo${PYTHONPATH:+:$PYTHONPATH} python scripts/probe_flash_prefill.py);;
    w4_probe)
      TMOS=1200; PAT='w4-kernel-probe OK'
      CMD=(env PYTHONPATH=/root/repo${PYTHONPATH:+:$PYTHONPATH} python scripts/probe_w4_kernel.py);;
    bench_14b)
      TMOS=5400; PAT='"value"'
      # Last-chance attempt (one failure already recorded): drop every
      # Pallas kernel — BENCH_ATTENTION_IMPL=xla takes the flash prefill
      # out of the picture too, so a kernel-specific remote Mosaic crash
      # cannot cost the 14B capacity number outright (the provisioner
      # chunks rows if einsum prefill transients run tight).
      XLA_LAST=()
      if [ -s "$OUT/bench_14b.fails" ]; then
        XLA_LAST=(BENCH_ATTENTION_IMPL=xla BCG_TPU_DISABLE_W4_KERNEL=1)
      fi
      CMD=(env BENCH_ROUNDS=2 BENCH_MODEL=bcg-tpu/bench-14b
           ${W4_FALLBACK[@]+"${W4_FALLBACK[@]}"}
           ${INT8_FALLBACK[@]+"${INT8_FALLBACK[@]}"}
           ${XLA_LAST[@]+"${XLA_LAST[@]}"} python bench.py);;
    bench_14b_kernel)
      # Kernel-ON 14B arm: only meaningful once the padded group-5
      # dispatch has hardware evidence (the probe's non-gating
      # "14b-group5-padded" INFO case) and the fallback 14B number
      # exists to compare against.  Guarded by run_step's pre-check.
      TMOS=5400; PAT='"value"'
      CMD=(env BENCH_ROUNDS=2 BENCH_MODEL=bcg-tpu/bench-14b
           BCG_TPU_ALLOW_PADDED_GROUP_KERNEL=1
           ${W4_FALLBACK[@]+"${W4_FALLBACK[@]}"} python bench.py);;
    parity_*)
      TMOS=5400; PAT='"aggregate"'
      CMD=(python -m bcg_tpu.experiments "${1#parity_}" --backend jax
           --model bcg-hf/bench-1b --runs 10 --rounds 8
           --concurrency 2 --seed 100);;
    *) return 1;;
  esac
}

# run_step <name>: execute the step's spec with stamping + triage.
run_step() {
  local name=$1
  [ -e "$OUT/$name.done" ] && return 0
  [ -e "$OUT/$name.skip" ] && return 0
  # bench_artifact is meaningful only with the artifact actually on
  # disk: without it, checkpoint discovery silently falls back to the
  # plain HF fixture and the step would re-measure bench_hf1b.
  if [ "$name" = bench_artifact ]; then
    if [ ! -f checkpoints_q/bcg-hf--bench-1b/bcg_tpu_quantized.json ]; then
      touch "$OUT/$name.skip"
      log "SKIP $name: no quantized artifact on disk (art_convert skipped or wiped)"
      return 0
    fi
  fi
  # The kernel-ON 14B arm needs hardware evidence for the padded
  # group-5 dispatch (both INFO cases OK) and the fallback number to
  # compare against — otherwise it would just re-crash or re-measure.
  if [ "$name" = bench_14b_kernel ]; then
    if ! { [ -e "$OUT/bench_14b.done" ] \
           && grep -q "14b-group5-padded/step.*info-OK" "$OUT/int8_probe.json" 2>/dev/null \
           && grep -q "14b-group5-padded/chunk.*info-OK" "$OUT/int8_probe.json" 2>/dev/null; }; then
      touch "$OUT/$name.skip"
      log "SKIP $name: padded-group kernel lacks probe evidence or no fallback 14B number"
      return 0
    fi
  fi
  step_spec "$name" || { log "BUG: no spec for step $name"; touch "$OUT/$name.skip"; return 0; }
  # Never START a step that could still be running at the deadline —
  # a leftover bench process would contend with the driver's own run.
  # Exception: bench_default gets a deadline-CAPPED attempt when >=10
  # min remain — even a partial run populates the persistent compile
  # cache with exactly the programs the driver's round-end bench needs
  # (observed: a killed 25-min attempt banked 71 cache entries), so a
  # late healthy window is spent warming rather than wasted.  The capped
  # run shares the normal execute/validate/triage path: only its
  # deadline KILL is non-evidence (no TMO count, no .skip) — a fast
  # deterministic crash inside the window is real evidence and still
  # .fails-counts.
  local capped=0
  if [ $(( $(date -u +%s) + TMOS )) -gt "${DEADLINE:-9999999999}" ]; then
    local room=$(( ${DEADLINE:-9999999999} - $(date -u +%s) - 90 ))
    if [ "$name" = bench_default ] && [ "$room" -ge 600 ]; then
      log "WARM $name: deadline-capped ${room}s attempt (compile-cache prewarm)"
      TMOS=$room
      capped=1
    else
      log "DEFER $name: its timeout window crosses the watcher deadline"
      return 2
    fi
  fi
  log "START $name"
  # -k 30: a bench stuck in an unkillable remote-compile RPC must not
  # outlive its window into the driver's bench slot (SIGKILL backstop
  # fits inside the warm path's 90 s deadline margin).
  timeout -k 30 "$TMOS" "${CMD[@]}" > "$OUT/$name.json" 2> "$OUT/$name.log"
  local rc=$?
  if [ $rc -eq 0 ] && grep -q "$PAT" "$OUT/$name.json" \
      && ! grep -qi '"error"' "$OUT/$name.json"; then
    touch "$OUT/$name.done"
    log "DONE $name: $(tail -c 300 "$OUT/$name.json" | tr '\n' ' ')"
    return 0
  fi
  # Availability failure (attach error, tunnel death): leave un-stamped
  # and signal the caller to go back to probing.  bench.py's "bench[..]:"
  # stage stamps are excluded first — a stamp whose wording happened to
  # contain a marker substring would otherwise turn every deterministic
  # failure of the step into an endless outage-retry loop.
  if grep -hv '^bench\[' "$OUT/$name.json" "$OUT/$name.log" 2>/dev/null \
      | grep -qiE "unavailable|attach|connection refused|response body closed"; then
    log "UNAVAIL $name rc=$rc — back to probing"
    return 2
  fi
  # A timeout can be a mid-step hang (chip died) OR a legitimately slow
  # step on healthy hardware.  Disambiguate with an immediate re-probe:
  # a dead chip means an outage timeout (retry forever, like UNAVAIL);
  # a healthy probe means the step itself is too slow — bound those so
  # one deterministically-slow step can't wedge the steps behind it.
  # 124 = SIGTERM kill; 137 = the -k SIGKILL backstop (process ignored
  # TERM) — both are "the window ended", not evidence about the step.
  if [ $rc -eq 124 ] || [ $rc -eq 137 ]; then
    if [ "$capped" = 1 ]; then
      # Deadline kill of a warm attempt: not evidence about the step —
      # the compile cache it banked is the point.
      log "WARM $name deadline kill (no stamp; cache retained)"
      return 2
    fi
    if ! probe; then
      log "TIMEOUT $name during outage (probe fails) — back to probing"
      return 2
    fi
    # In-memory counter (not a stamp file): an outage that ends just
    # before the re-probe would be misattributed as a healthy-hardware
    # timeout, and persisting that across watcher restarts could
    # permanently skip a healthy step after a few flappy windows.
    TMO[$name]=$(( ${TMO[$name]:-0} + 1 ))
    local tmos=${TMO[$name]}
    log "TIMEOUT $name on healthy hardware attempt=$tmos"
    if [ "$tmos" -ge 3 ]; then
      touch "$OUT/$name.skip"
      log "SKIP $name after $tmos healthy-hardware timeouts"
      return 0  # settled (like .done): drain continues to the next step
    fi
    return 3  # healthy-hardware timeout: re-probe, but DON'T reset TMO
  fi
  local fails=$(( $(cat "$OUT/$name.fails" 2>/dev/null || echo 0) + 1 ))
  echo "$fails" > "$OUT/$name.fails"
  log "FAIL $name rc=$rc attempt=$fails: $(tail -c 300 "$OUT/$name.log" | tr '\n' ' ')"
  if [ "$fails" -ge 2 ]; then
    touch "$OUT/$name.skip"
    log "SKIP $name after $fails failures"
    return 0  # settled: drain continues to the next step
  fi
  return 1
}

drain() {
  local s
  for s in $STEPS; do
    run_step "$s" || return $?
  done
  return 0
}

all_done() {
  local s
  for s in $STEPS; do
    [ -e "$OUT/$s.done" ] || [ -e "$OUT/$s.skip" ] || return 1
  done
  return 0
}

# Hard deadline (epoch seconds; env-overridable): the watcher must be
# gone before the round driver runs its own bench — two engines
# contending for one 16 GB chip would OOM the driver's recorded number.
# Default: 6 h from launch — a stale hardcoded epoch once made the
# watcher exit on its first loop iteration.  Set HW_WATCHER_DEADLINE
# explicitly to end just before the driver's bench window.
DEADLINE=${HW_WATCHER_DEADLINE:-$(( $(date -u +%s) + 21600 ))}

log "watcher started (pid $$)"
while true; do
  if [ "$(date -u +%s)" -ge "$DEADLINE" ]; then
    log "deadline reached — exiting to leave the chip to the driver"
    exit 0
  fi
  if all_done; then log "queue fully drained — exiting"; exit 0; fi
  if probe; then
    log "probe OK — draining queue"
    drain
    rc=$?
    if [ $rc -eq 0 ]; then
      # A full pass with nothing left raises all_done next iteration; a
      # pass that settled everything reachable still sleeps so a probe
      # loop can never spin hot against the chip.
      all_done && continue
    else
      log "drain interrupted rc=$rc"
      # rc=2 means an outage was observed mid-drain (UNAVAIL or a
      # timeout whose re-probe failed): same invalidation as a failed
      # top-level probe — healthy-timeout attribution starts over.
      # rc=3 (healthy-hardware timeout) keeps its count: wiping it here
      # would make the 3-strike skip unreachable.
      [ $rc -eq 2 ] && TMO=()
    fi
  else
    log "probe failed (tpu not ready)"
    # An observed outage invalidates the healthy-timeout attribution:
    # any step timeout counted during a flappy window may have been the
    # outage's fault, so start the 3-strike count over.
    TMO=()
  fi
  sleep 300
done
