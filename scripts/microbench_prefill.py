#!/usr/bin/env python
"""Microbenchmark prefill components at game shapes, IN-LOOP.

Round-3 measured prefill at 15.8% MFU while decode sits at 88% of the
HBM roof — prefill is now the larger half of round time, and the bench
cannot say WHERE the other 84% goes (the axon tunnel's ~1-2 ms
dispatch floor hides per-op costs).  Like
``microbench_decode_attention.py``, every op here runs N times inside
ONE jitted ``fori_loop`` with a serializing data dependency, so the
per-iteration number is the in-loop cost.

Measured components at bench-1b layer dims (B=10, L=2048, D=2048,
H=16/Hkv=8/Dh=128, F=6144):

- each projection matmul in bf16 vs int8 W8A8 (``quantize.dense``:
  act-quant + int8 dot + rescale) vs int4 W4A16 (XLA dequant fallback —
  the prefill path of ``dense``),
- flash-attention prefill (Pallas) vs the blockwise-scan fallback,
- rope rotation,
- rmsnorm,
- a FULL transformer layer via the same primitives chained.

Prints per-op ms/iter, achieved TFLOP/s, and % of the v5e peak for the
op's dtype (bf16 197 / int8 394 TFLOP/s) so the MFU gap decomposes.

Usage (on the TPU):  python scripts/microbench_prefill.py
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from bcg_tpu.models.configs import spec_for_model
from bcg_tpu.models.quantize import dense, quantize_weight, quantize_weight_int4
from bcg_tpu.models.transformer import apply_rope, rms_norm, rope_table
from bcg_tpu.ops.attention import blockwise_attention, flash_attention
from bcg_tpu.runtime.envflags import get_bool, get_int

ITERS = get_int("MB_ITERS")
PEAK_BF16 = 197e12
PEAK_INT8 = 394e12


def loop_time(body, carry0, iters=ITERS):
    @jax.jit
    def run(carry):
        return jax.lax.fori_loop(0, iters, body, carry)

    out = run(carry0)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = run(carry0)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def feedback(x, out):
    """Fold a scalar of ``out`` back into ``x`` to serialize iterations."""
    s = out.astype(jnp.float32).mean() * 1e-20
    return x + s.astype(x.dtype)


def bench_matmul(name, x, w, flops, peak):
    def body(i, carry):
        xx, acc = carry
        out = dense(xx, w)
        return (feedback(xx, out), acc + out.astype(jnp.float32).mean())

    dt = loop_time(body, (x, jnp.float32(0)))
    print(f"  {name:<28s} {dt*1e3:7.2f} ms  {flops/dt/1e12:6.1f} TF/s"
          f"  {100*flops/dt/peak:5.1f}% peak")
    return dt


def main():
    B = get_int("MB_B")
    L = get_int("MB_L")
    spec = spec_for_model("bcg-tpu/bench-1b")
    D, H, Hkv, Dh, F = 2048, 16, 8, 128, 6144
    if get_bool("MB_TINY"):  # CPU smoke: shrink every dim
        B, L, D, H, Hkv, Dh, F = 2, 64, 64, 2, 1, 32, 128
    S = L  # self-attention over the fresh prompt
    rng = np.random.default_rng(0)
    print(f"prefill shapes: B={B} L={L} D={D} H={H} Hkv={Hkv} Dh={Dh} F={F}"
          f"  ({ITERS} in-loop iterations; backend={jax.default_backend()})")

    x = jnp.asarray(rng.standard_normal((B, L, D)) * 0.02, jnp.bfloat16)
    BL = B * L

    shapes = {
        "qkv": (D, (H + 2 * Hkv) * Dh),
        "o": (H * Dh, D),
        "gate_up": (D, 2 * F),
        "down": (F, D),
    }
    ws = {k: jnp.asarray(rng.standard_normal(s) * 0.02, jnp.bfloat16)
          for k, s in shapes.items()}
    mode_weights = {
        "bf16": ws,
        "int8": {k: quantize_weight(v) for k, v in ws.items()},
        "int4": {k: quantize_weight_int4(v) for k, v in ws.items()},
    }

    total = {"bf16": 0.0, "int8": 0.0, "int4": 0.0}
    mm_flops = 0
    for k, (din, dout) in shapes.items():
        xin = x if din == D else jnp.asarray(
            rng.standard_normal((B, L, din)) * 0.02, jnp.bfloat16)
        fl = 2 * BL * din * dout
        mm_flops += fl
        total["bf16"] += bench_matmul(
            f"{k} bf16", xin, mode_weights["bf16"][k], fl, PEAK_BF16)
        total["int8"] += bench_matmul(
            f"{k} int8 W8A8", xin, mode_weights["int8"][k], fl, PEAK_INT8)
        total["int4"] += bench_matmul(
            f"{k} int4 W4A16", xin, mode_weights["int4"][k], fl, PEAK_BF16)

    # Attention at prefill shapes, causal mask.
    q = jnp.asarray(rng.standard_normal((B, L, H, Dh)) * 0.1, jnp.bfloat16)
    k_ = jnp.asarray(rng.standard_normal((B, S, Hkv, Dh)) * 0.1, jnp.bfloat16)
    v_ = jnp.asarray(rng.standard_normal((B, S, Hkv, Dh)) * 0.1, jnp.bfloat16)
    causal = jnp.asarray(
        np.tril(np.ones((L, S), bool))[None].repeat(B, 0))
    scale = Dh ** -0.5
    # ~half the score/AV work survives the causal mask.
    attn_flops = 2 * 2 * B * H * L * S * Dh // 2

    flash_dt = 0.0
    for name, fn in (("flash_attention (Pallas)", flash_attention),
                     ("blockwise_attention (XLA)", blockwise_attention)):
        def body(i, carry, fn=fn):
            qq, acc = carry
            out = fn(qq, k_, v_, causal, scale)
            return (feedback(qq, out), acc + out.astype(jnp.float32).mean())

        dt = loop_time(body, (q, jnp.float32(0)))
        if fn is flash_attention:
            flash_dt = dt  # subtracted from the full-layer gap below
        print(f"  {name:<28s} {dt*1e3:7.2f} ms  {attn_flops/dt/1e12:6.1f} TF/s"
              f"  {100*attn_flops/dt/PEAK_BF16:5.1f}% peak")

    # A/B against the OFFICIAL jax pallas TPU flash kernel (no GQA: KV
    # repeated to H heads, so it carries group x the KV bytes — prefill
    # at these shapes is compute-dominated, so the comparison is still
    # apples-to-apples on the score/AV pipeline).
    try:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as jax_flash,
        )

        group = H // Hkv
        kr = jnp.repeat(k_, group, axis=2).transpose(0, 2, 1, 3)  # [B,H,S,Dh]
        vr = jnp.repeat(v_, group, axis=2).transpose(0, 2, 1, 3)

        def jf_body(i, carry):
            qq, acc = carry
            out = jax_flash(
                qq.transpose(0, 2, 1, 3), kr, vr,
                causal=True, sm_scale=scale,
            )
            out = out.transpose(0, 2, 1, 3)
            return (feedback(qq, out), acc + out.astype(jnp.float32).mean())

        dt = loop_time(jf_body, (q, jnp.float32(0)))
        print(f"  {'official jax tpu flash':<28s} {dt*1e3:7.2f} ms  "
              f"{attn_flops/dt/1e12:6.1f} TF/s"
              f"  {100*attn_flops/dt/PEAK_BF16:5.1f}% peak")
    except Exception as exc:  # noqa: BLE001 — comparison point, not critical
        print(f"  official jax tpu flash: unavailable ({type(exc).__name__}: "
              f"{str(exc)[:120]})")

    # A/B against the official SPLASH kernel, GQA-NATIVE via the MQA
    # variant (per kv-head: `group` query heads share one KV stream —
    # no KV repeat, unlike the flash row above).  q is pre-scaled
    # (splash applies no sm_scale itself).
    try:
        from jax.experimental.pallas.ops.tpu.splash_attention import (
            splash_attention_kernel as sk,
            splash_attention_mask as sm,
        )

        group = H // Hkv
        smask = sm.MultiHeadMask([sm.CausalMask((L, S)) for _ in range(group)])
        mqa = sk.make_splash_mqa(smask, head_shards=1, q_seq_shards=1,
                                 block_sizes=sk.BlockSizes.get_default())
        splash_fn = jax.vmap(jax.vmap(mqa))  # over batch, then kv-head

        kg = k_.transpose(0, 2, 1, 3)                      # [B,Hkv,S,Dh]
        vg = v_.transpose(0, 2, 1, 3)

        def sp_body(i, carry):
            qq, acc = carry
            qg2 = (qq * scale).transpose(0, 2, 1, 3).reshape(
                B, Hkv, group, L, Dh)
            out = splash_fn(qg2, kg, vg)                   # [B,Hkv,g,L,Dh]
            out = out.reshape(B, H, L, Dh).transpose(0, 2, 1, 3)
            return (feedback(qq, out), acc + out.astype(jnp.float32).mean())

        dt = loop_time(sp_body, (q, jnp.float32(0)))
        print(f"  {'official splash (GQA-mqa)':<28s} {dt*1e3:7.2f} ms  "
              f"{attn_flops/dt/1e12:6.1f} TF/s"
              f"  {100*attn_flops/dt/PEAK_BF16:5.1f}% peak")
    except Exception as exc:  # noqa: BLE001 — comparison point, not critical
        print(f"  official splash: unavailable ({type(exc).__name__}: "
              f"{str(exc)[:120]})")

    # Rope + rmsnorm via the PRODUCTION ops (transformer.py) at the
    # spec's constants, so the microbench measures the real code path
    # (bandwidth-bound elementwise; report ms + GB/s).
    positions = jnp.broadcast_to(jnp.arange(L), (B, L))
    cos, sin = rope_table(positions, Dh, spec.rope_theta)

    def rope_body(i, carry):
        qq, acc = carry
        rot = apply_rope(qq, cos, sin)
        return (feedback(qq, rot), acc + rot.astype(jnp.float32).mean())

    dt = loop_time(rope_body, (q, jnp.float32(0)))
    gb = 2 * q.size * 2 / 1e9
    print(f"  {'rope (q-side)':<28s} {dt*1e3:7.2f} ms  {gb/dt:6.1f} GB/s")

    g = jnp.ones((D,), jnp.bfloat16)

    def norm_body(i, carry):
        xx, acc = carry
        out = rms_norm(xx, g, spec.rms_eps)
        return (feedback(xx, out), acc + out.astype(jnp.float32).mean())

    dt = loop_time(norm_body, (x, jnp.float32(0)))
    gb = 2 * x.size * 2 / 1e9
    print(f"  {'rmsnorm':<28s} {dt*1e3:7.2f} ms  {gb/dt:6.1f} GB/s")

    # FULL layer chained from the same primitives: norm -> qkv ->
    # qk-norm -> rope -> flash attn -> o -> norm -> gate/up ->
    # (silu*mul) -> down, with residual adds.  The chained number
    # exposes fusion/dispatch gaps the per-op numbers hide.
    g_qk = jnp.ones((Dh,), jnp.bfloat16)
    def full_layer(xx, wmode):
        w = mode_weights[wmode]
        h = xx
        hn = rms_norm(h, g, spec.rms_eps)
        qkv = dense(hn, w["qkv"])
        qh = qkv[..., :H * Dh].reshape(B, L, H, Dh)
        kh = qkv[..., H * Dh:(H + Hkv) * Dh].reshape(B, L, Hkv, Dh)
        vh = qkv[..., (H + Hkv) * Dh:].reshape(B, L, Hkv, Dh)
        if spec.qk_norm:  # bench-1b has per-head q/k norms (Qwen3-style)
            qh = rms_norm(qh, g_qk, spec.rms_eps)
            kh = rms_norm(kh, g_qk, spec.rms_eps)
        qh = apply_rope(qh, cos, sin)
        kh = apply_rope(kh, cos, sin)
        attn = flash_attention(qh, kh, vh, causal, scale)
        h = h + dense(attn.reshape(B, L, H * Dh), w["o"])
        hn = rms_norm(h, g, spec.rms_eps)
        gu = dense(hn, w["gate_up"])
        gate, up = jnp.split(gu, 2, axis=-1)
        act = jax.nn.silu(gate.astype(jnp.float32)).astype(h.dtype) * up
        return h + dense(act, w["down"])

    layer_flops = mm_flops + attn_flops
    for mode in ("bf16", "int8", "int4"):
        def body(i, carry, mode=mode):
            xx, acc = carry
            out = full_layer(xx, mode)
            return (feedback(xx, out), acc + out.astype(jnp.float32).mean())

        dt = loop_time(body, (x, jnp.float32(0)))
        gap = dt - total[mode] - flash_dt
        print(f"  full layer {mode:<17s} {dt*1e3:7.2f} ms "
              f" {layer_flops/dt/1e12:6.1f} TF/s "
              f" (vs matmuls {total[mode]*1e3:.2f} + attn {flash_dt*1e3:.2f} ms;"
              f" elementwise+fusion gap {gap*1e3:.2f} ms)")
    print(f"  layer matmul-only roofline: {mm_flops/PEAK_BF16*1e3:.2f} ms bf16"
          f" / {mm_flops/PEAK_INT8*1e3:.2f} ms int8;"
          f" attn roofline {attn_flops/PEAK_BF16*1e3:.2f} ms bf16")


if __name__ == "__main__":
    main()
