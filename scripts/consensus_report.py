#!/usr/bin/env python
"""Convergence report from game-event JSONL files (BCG_TPU_GAME_EVENTS).

``python scripts/consensus_report.py EVENTS.jsonl [MORE.jsonl ...] [--rounds]``

Aggregates one or many game-event streams (each written by
``bcg_tpu.obs.game_events``, first line = run manifest) into the sweep
tables the paper's evaluation methodology needs: convergence rate,
rounds-to-consensus, and Byzantine influence, grouped by configuration
— plus, when games carry a ``strategy`` field (scenario-registry
runs), a per-strategy table with an equivocation tabulation (rows
where one sender's delivered values differ across receivers).
Merging many files is mechanical BECAUSE of the manifest header — the
group key is (agents split, topology, model, flag overrides), all read
from ``manifest`` + ``game_start`` records, never from filenames.  The
stamped fleet identity (run_id + process@host) is accounted inside
each row: N rank files of one multi-process run report as ONE run with
N ranks, while N independently-seeded single-process runs of the same
config still aggregate into one row with a meaningful convergence
rate.

Self-contained — no bcg_tpu import — so event files copied off a TPU
host (or collected from a hundred sweep workers) can be aggregated
anywhere.  Tolerant by design: the emitting sink drops the OLDEST
records under backpressure, so a game may be missing its ``game_start``
(grouped under the file manifest with unknown geometry) or its
``game_end`` (counted as incomplete and excluded from the convergence
rate, never guessed).  Unknown schema versions are reported, not
silently merged.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

# The schema this report understands (mirrors
# bcg_tpu.obs.export.EVENT_SCHEMA_VERSION — by value, not import).
KNOWN_SCHEMA_VERSIONS = (1,)

# Flags that vary per worker without changing game semantics — excluded
# from the group key so one sweep's workers merge into one row (the
# fleet plane's per-worker knobs included: a run id is the GROUP key
# itself, never a config axis).
_NON_CONFIG_FLAGS = (
    "BCG_TPU_GAME_EVENTS",
    "BCG_TPU_SERVE_EVENTS",
    "BCG_TPU_METRICS_PORT",
    "BCG_TPU_TRACE_OUT",
    "BCG_TPU_RUN_ID",
    "BCG_TPU_FLEET",
    "BCG_TPU_METRICS_SHARD_DIR",
    "BCG_TPU_METRICS_SHARD_MS",
    "BCG_TPU_FLEET_STRAGGLER_FACTOR",
)


class GameAgg:
    """Accumulator for one game's records."""

    __slots__ = ("config_key", "run_id", "rank", "started", "ended",
                 "converged", "rounds_to_consensus", "influence",
                 "round_ms", "decisions", "fallbacks", "invalids", "job",
                 "strategy", "equivocation_rows")

    def __init__(self, config_key: str, run_id: str = "-",
                 rank: str = "-"):
        self.config_key = config_key
        # Sweep-tier job id (bcg_tpu/sweep stamps it on game_start/
        # game_end): stable across processes, so a job that ran twice —
        # the resume bug class — is detectable as two ENDED games
        # sharing one job id (duplicate_job_problems).
        self.job: Optional[str] = None
        # Run identity from the stamped manifest: every rank of one
        # multi-process run shares run_id (BCG_TPU_RUN_ID), so its
        # files merge into ONE run row instead of reading as N
        # independent runs; rank = "process@host" provenance.
        self.run_id = run_id
        self.rank = rank
        self.started = False
        self.ended = False
        self.converged = False
        self.rounds_to_consensus: Optional[int] = None
        self.influence = 0
        self.round_ms: List[float] = []
        self.decisions = 0
        self.fallbacks = 0
        self.invalids = 0
        # Adversary strategy stamped in game_start (scenario registry);
        # None for streams written before the strategy field existed.
        self.strategy: Optional[str] = None
        # (round, sender) pairs whose delivered values DIFFER across
        # receivers — the equivocation signature, tabulated from the
        # per-receiver ``values`` field of deliveries records.
        self.equivocation_rows = 0


def _config_key(manifest: Dict, start: Optional[Dict]) -> str:
    """Human-readable group key from manifest + game_start fields."""
    parts = []
    if start:
        parts.append(
            f"{start.get('num_honest', '?')}h+"
            f"{start.get('num_byzantine', '?')}b"
        )
        if start.get("topology"):
            parts.append(str(start["topology"]))
        if start.get("model"):
            parts.append(str(start["model"]))
        if start.get("strategy"):
            parts.append(f"strategy={start['strategy']}")
        # Awareness only when it deviates from the default — keeps
        # pre-strategy rows and may_exist rows keyed identically.
        if start.get("awareness") and start["awareness"] != "may_exist":
            parts.append(f"awareness={start['awareness']}")
    elif manifest.get("preset"):
        parts.append(str(manifest["preset"]))
    flags = manifest.get("flags") or {}
    for name in sorted(flags):
        if name in _NON_CONFIG_FLAGS:
            continue
        parts.append(f"{name}={flags[name]}")
    return " ".join(parts) if parts else "(unknown config)"


def _run_identity(manifest: Dict) -> Tuple[str, str]:
    """(run_id, rank) from a stamped manifest — ranks of one run share
    run_id, so their files group into one run; older unstamped files
    fall back to "-" and group as before."""
    run = str(manifest.get("run_id") or "-")
    proc = manifest.get("process_index")
    host = manifest.get("host")
    if proc is None and host is None:
        return run, "-"
    return run, f"{proc if proc is not None else '?'}@{host or '?'}"


def parse_file(path: str, problems: List[str]) -> List[GameAgg]:
    """All games found in one event file (games still open at EOF stay
    ``ended=False``)."""
    manifest: Dict = {}
    games: Dict[str, GameAgg] = {}
    starts: Dict[str, Dict] = {}
    # game -> (round, sender) -> delivered-value set, from deliveries
    # records that carry per-receiver values; a set with >1 member is
    # one equivocation row (same sender, same round, different values
    # at different receivers).
    equiv_seen: Dict[str, Dict[Tuple[int, str], set]] = {}
    bad_lines = 0
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                bad_lines += 1
                continue
            event = rec.get("event")
            if event == "manifest":
                manifest = rec
                version = rec.get("schema_version")
                if version not in KNOWN_SCHEMA_VERSIONS:
                    problems.append(
                        f"{path}: unknown schema_version {version!r} "
                        f"(this report understands {KNOWN_SCHEMA_VERSIONS})"
                    )
                continue
            gid = rec.get("game")
            if gid is None:
                continue
            run, rank = _run_identity(manifest)
            if event == "game_start":
                starts[gid] = rec
                agg = games.get(gid) or GameAgg(
                    _config_key(manifest, rec), run, rank
                )
                agg.config_key = _config_key(manifest, rec)
                agg.started = True
                if rec.get("job"):
                    agg.job = str(rec["job"])
                if rec.get("strategy"):
                    agg.strategy = str(rec["strategy"])
                games[gid] = agg
                continue
            agg = games.get(gid)
            if agg is None:
                # game_start lost to sink backpressure: group under the
                # file manifest alone.
                agg = games[gid] = GameAgg(
                    _config_key(manifest, None), run, rank
                )
            if event == "round_end":
                agg.influence += int(rec.get("byzantine_influence", 0))
                if rec.get("duration_ms") is not None:
                    agg.round_ms.append(float(rec["duration_ms"]))
                if (rec.get("has_consensus")
                        and agg.rounds_to_consensus is None):
                    agg.rounds_to_consensus = int(rec.get("round", 0))
            elif event == "decision":
                agg.decisions += 1
                outcome = rec.get("outcome")
                if outcome == "fallback":
                    agg.fallbacks += 1
                elif outcome == "invalid":
                    agg.invalids += 1
            elif event == "deliveries" and rec.get("values") is not None:
                per = equiv_seen.setdefault(gid, {})
                rnd = rec.get("round")
                for sender, val in zip(rec.get("senders") or (),
                                       rec["values"]):
                    per.setdefault((rnd, sender), set()).add(val)
            elif event == "game_end":
                agg.ended = True
                agg.converged = bool(rec.get("converged"))
                if rec.get("job"):
                    agg.job = str(rec["job"])
                # game_end's cumulative count is authoritative when
                # round_end records were dropped.
                agg.influence = max(
                    agg.influence, int(rec.get("byzantine_influence", 0))
                )
    if bad_lines:
        problems.append(f"{path}: skipped {bad_lines} unparseable line(s)")
    for gid, per in equiv_seen.items():
        agg = games.get(gid)
        if agg is not None:
            agg.equivocation_rows = sum(
                1 for vals in per.values() if len(vals) > 1
            )
    return list(games.values())


def duplicate_job_problems(games: List[GameAgg]) -> List[str]:
    """Sweep-integrity check: a job id (bcg_tpu/sweep) with MORE THAN
    ONE ended game across the merged files means a job ran twice — the
    exact resume bug the sweep manifest exists to prevent, and a silent
    corruption of every per-config denominator.  Reported as a WARNING
    line (the tables still render; the duplicate rows are visible)."""
    counts: Dict[str, int] = defaultdict(int)
    for g in games:
        if g.ended and g.job:
            counts[g.job] += 1
    return [
        f"job {job!r} has {n} game_end records across the merged files "
        "— a sweep job ran to completion twice (resume bug)"
        for job, n in sorted(counts.items()) if n > 1
    ]


def _median(ordered: List[float]) -> float:
    if not ordered:
        return 0.0
    n = len(ordered)
    mid = n // 2
    if n % 2:
        return float(ordered[mid])
    return (ordered[mid - 1] + ordered[mid]) / 2.0


def render_report(games: List[GameAgg], problems: List[str]) -> str:
    # Rows stay CONFIG-keyed (a sweep of N independent seeded runs of
    # one config must aggregate into one row with a meaningful
    # convergence rate — the PAPERS.md methodology), but the stamped
    # manifest identity is now accounted INSIDE the row: `runs` counts
    # distinct run_ids and `ranks` distinct (run_id, process@host)
    # contributors, so a 2-rank fleet run reads as ONE run with 2
    # ranks, not as two independent runs.  Unstamped files fall back to
    # run "-"/rank "-" and group exactly as before.
    by_config: Dict[str, List[GameAgg]] = defaultdict(list)
    for g in games:
        by_config[g.config_key].append(g)

    lines: List[str] = []
    header = (
        f"{'runs':>4}  {'ranks':>5}  {'games':>5}  {'done':>4}  "
        f"{'conv':>4}  {'rate':>6}  "
        f"{'rounds(med/mean)':>16}  {'byz_infl':>8}  "
        f"{'fallback':>8}  {'invalid':>7}  config"
    )
    lines.append("== consensus outcomes by config ==")
    lines.append(header)
    for key in sorted(by_config):
        group = by_config[key]
        runs = {g.run_id for g in group}
        ranks = {(g.run_id, g.rank) for g in group if g.rank != "-"}
        done = [g for g in group if g.ended]
        conv = [g for g in done if g.converged]
        rate = (100.0 * len(conv) / len(done)) if done else 0.0
        to_consensus = sorted(
            g.rounds_to_consensus for g in conv
            if g.rounds_to_consensus is not None
        )
        med = _median(to_consensus)
        mean = (sum(to_consensus) / len(to_consensus)) if to_consensus else 0.0
        infl = sum(g.influence for g in done)
        decisions = sum(g.decisions for g in group)
        fallbacks = sum(g.fallbacks for g in group)
        invalids = sum(g.invalids for g in group)
        fb_pct = (100.0 * fallbacks / decisions) if decisions else 0.0
        inv_pct = (100.0 * invalids / decisions) if decisions else 0.0
        lines.append(
            f"{len(runs):>4}  {len(ranks) or len(runs):>5}  "
            f"{len(group):>5}  {len(done):>4}  {len(conv):>4}  "
            f"{rate:>5.1f}%  {med:>7.1f}/{mean:<8.1f}  {infl:>8}  "
            f"{fb_pct:>7.1f}%  {inv_pct:>6.1f}%  {key}"
        )

    round_ms = sorted(ms for g in games for ms in g.round_ms)
    if round_ms:
        n = len(round_ms)
        p50 = round_ms[min(n - 1, int(round(0.50 * (n - 1))))]
        p95 = round_ms[min(n - 1, int(round(0.95 * (n - 1))))]
        lines.append("")
        lines.append(
            f"== round duration: {n} rounds, p50 {p50:.1f} ms, "
            f"p95 {p95:.1f} ms =="
        )
    incomplete = sum(1 for g in games if not g.ended)
    if incomplete:
        lines.append("")
        lines.append(
            f"({incomplete} game(s) without a game_end record — excluded "
            "from convergence rate)"
        )
    for problem in problems:
        lines.append(f"WARNING: {problem}")
    return "\n".join(lines)


def render_strategies(games: List[GameAgg]) -> str:
    """Per-strategy table: the adversary-library readout.  Groups by
    the strategy stamped in game_start (scenario-registry runs), so a
    registry sweep reads as one row per Byzantine strategy regardless
    of topology/channel/seed spread.  ``equiv_rows`` counts (round,
    sender) pairs whose delivered values differed across receivers —
    nonzero ONLY under an equivocating adversary, and the acceptance
    signal the perf gate's scenarios arm floors."""
    by_strat: Dict[str, List[GameAgg]] = defaultdict(list)
    for g in games:
        if g.strategy:
            by_strat[g.strategy].append(g)
    if not by_strat:
        return ""
    lines = ["== outcomes by adversary strategy =="]
    lines.append(
        f"{'strategy':<12}  {'games':>5}  {'done':>4}  {'conv':>4}  "
        f"{'rate':>6}  {'rounds(med/mean)':>16}  {'byz_infl':>8}  "
        f"{'equiv_rows':>10}"
    )
    for strat in sorted(by_strat):
        group = by_strat[strat]
        done = [g for g in group if g.ended]
        conv = [g for g in done if g.converged]
        rate = (100.0 * len(conv) / len(done)) if done else 0.0
        to_consensus = sorted(
            g.rounds_to_consensus for g in conv
            if g.rounds_to_consensus is not None
        )
        med = _median(to_consensus)
        mean = (sum(to_consensus) / len(to_consensus)) if to_consensus else 0.0
        infl = sum(g.influence for g in done)
        equiv = sum(g.equivocation_rows for g in group)
        lines.append(
            f"{strat:<12}  {len(group):>5}  {len(done):>4}  "
            f"{len(conv):>4}  {rate:>5.1f}%  {med:>7.1f}/{mean:<8.1f}  "
            f"{infl:>8}  {equiv:>10}"
        )
    return "\n".join(lines)


def render_rounds(games: List[GameAgg]) -> str:
    """--rounds: distribution of rounds-to-consensus over converged
    games (sweep plots read this table)."""
    counts: Dict[int, int] = defaultdict(int)
    for g in games:
        if g.ended and g.converged and g.rounds_to_consensus is not None:
            counts[g.rounds_to_consensus] += 1
    if not counts:
        return "== rounds-to-consensus: no converged games =="
    lines = ["== rounds-to-consensus distribution =="]
    width = max(counts.values())
    for rounds in sorted(counts):
        n = counts[rounds]
        bar = "#" * max(1, round(40 * n / width))
        lines.append(f"{rounds:>4} rounds  {n:>5}  {bar}")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Convergence-rate / rounds-to-consensus / Byzantine-"
        "influence tables from BCG_TPU_GAME_EVENTS JSONL files."
    )
    parser.add_argument("events", nargs="+",
                        help="one or more game-event JSONL paths")
    parser.add_argument("--rounds", action="store_true",
                        help="also print the rounds-to-consensus "
                        "distribution over converged games")
    args = parser.parse_args(argv)
    problems: List[str] = []
    games: List[GameAgg] = []
    for path in args.events:
        try:
            games.extend(parse_file(path, problems))
        except OSError as exc:
            print(f"consensus_report: cannot read {path}: {exc}",
                  file=sys.stderr)
            return 1
    if not games:
        print("consensus_report: no game records found", file=sys.stderr)
        for problem in problems:
            print(f"WARNING: {problem}", file=sys.stderr)
        return 1
    problems.extend(duplicate_job_problems(games))
    print(render_report(games, problems))
    strategies = render_strategies(games)
    if strategies:
        print()
        print(strategies)
    if args.rounds:
        print()
        print(render_rounds(games))
    return 0


if __name__ == "__main__":
    sys.exit(main())
