#!/usr/bin/env python
"""Render the hardware-watcher queue results into a markdown table.

Reads ``results/hw_r4/*.json`` (each the single-line bench JSON, or an
experiments-aggregate JSON for parity_* steps) and prints a
BENCH_NOTES-ready summary: one row per completed bench step with dec/s,
round rate, cold-boot seconds and the headline perf keys, plus a
parity-aggregate block.  Steps not yet stamped .done are listed as
pending so a partial drain still reports cleanly.

Usage:  python scripts/hw_queue_report.py [results_dir]
"""

from __future__ import annotations

import glob
import json
import os
import sys


def _load(path: str):
    try:
        with open(path) as f:
            text = f.read().strip()
        if not text:
            return None
        # bench.py prints exactly one JSON line; experiments print a
        # pretty-printed object. Either way: last JSON value in the file.
        return json.loads(text.splitlines()[-1]) if text[0] != "{" else json.loads(text)
    except (json.JSONDecodeError, OSError):
        return None


def main() -> None:
    if len(sys.argv) > 1:
        out_dir = sys.argv[1]
    else:
        # Newest round by NUMERIC suffix, directories only (lexicographic
        # max would pick hw_r9 over hw_r10, or a stray hw_r5.tar file).
        rounds = [
            d for d in glob.glob("results/hw_r*")
            if os.path.isdir(d) and d.rsplit("hw_r", 1)[1].isdigit()
        ]
        out_dir = (
            max(rounds, key=lambda d: int(d.rsplit("hw_r", 1)[1]))
            if rounds else "results/hw_r4"
        )
    names = sorted(
        os.path.basename(p)[:-5]
        for p in glob.glob(os.path.join(out_dir, "*.json"))
    )
    bench_rows, parity_blocks, pending, skipped = [], [], [], []
    for name in names:
        done = os.path.exists(os.path.join(out_dir, f"{name}.done"))
        skip = os.path.exists(os.path.join(out_dir, f"{name}.skip"))
        data = _load(os.path.join(out_dir, f"{name}.json"))
        if skip:
            skipped.append(name)
            continue
        if (
            not isinstance(data, dict)
            or (not done and "value" not in data and "aggregate" not in data)
        ):
            pending.append(name)
            continue
        if not done and data.get("error"):
            # Failed attempt awaiting retry: a 0.0-value error JSON is
            # not a measurement.
            pending.append(f"{name} (failed: {str(data['error'])[:60]})")
            continue
        if not done:
            # Parseable result without a stamp (e.g. a manually-renamed
            # A/B arm like bench_int8kv_nokernel): report it, marked.
            name += " (unstamped)"
        if "aggregate" in data:
            parity_blocks.append((name, data))
            continue
        extra = data.get("extra", {})
        bench_rows.append({
            "step": name,
            "dec/s": data.get("value"),
            "rounds/s": extra.get("rounds_per_sec"),
            "boot+r1 s": extra.get("boot_plus_first_round_s"),
            "prefill_mfu": extra.get("prefill_mfu"),
            "decode_gbps": extra.get("decode_gbps"),
            "ckpt": extra.get("checkpoint"),
            "kv": extra.get("kv_cache_dtype"),
            "quant": extra.get("quantization"),
        })

    if bench_rows:
        cols = list(bench_rows[0])
        print("| " + " | ".join(cols) + " |")
        print("|" + "---|" * len(cols))
        for r in bench_rows:
            print("| " + " | ".join(
                "-" if r[c] is None else str(r[c]) for c in cols) + " |")
    for name, data in parity_blocks:
        agg = data["aggregate"]
        print(f"\n### {name}")
        for k in ("runs", "consensus_rate", "mean_rounds",
                  "mean_quality_score", "outcomes"):
            if k in agg:
                print(f"- {k}: {agg[k]}")
    if pending:
        print("\npending:", ", ".join(pending))
    if skipped:
        print("skipped:", ", ".join(skipped))


if __name__ == "__main__":
    main()
