#!/usr/bin/env python
"""Microbenchmark decode-step components at game shapes, IN-LOOP.

The axon tunnel adds ~1-2 ms dispatch latency per device call, so
per-call timing is latency-floored and meaningless for ops that run
inside the decode ``lax.while_loop``.  Every measurement here runs the
op N times inside ONE jitted ``fori_loop`` with a serializing data
dependency, so the reported per-iteration cost is the in-loop cost.

Motivated by round-3: the int8-KV decode loop measured 9.0 ms/step vs
bf16's 5.1 while carrying ~2/3 the traffic.  Suspects: the Pallas
kernel's achieved bandwidth, and the quantize+scatter cache writes.

Usage (on the TPU):  python scripts/microbench_decode_attention.py
"""

from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from bcg_tpu.ops.decode_attention import (
    chunk_decode_attention,
    decode_attention,
    quantize_kv,
)

ITERS = 100


def loop_time(make_body, carry0, iters=ITERS):
    """Time ``iters`` sequential in-loop applications of ``make_body``
    inside one jit; returns seconds per iteration."""

    @jax.jit
    def run(carry):
        return jax.lax.fori_loop(0, iters, make_body, carry)

    out = run(carry0)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = run(carry0)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main():
    B, H, Hkv, Dh, S = 10, 16, 8, 128, 4096
    K = 8
    scale = Dh ** -0.5
    rng = np.random.default_rng(0)
    q0 = jnp.asarray(rng.standard_normal((B, H, Dh)), jnp.bfloat16)
    qk0 = jnp.asarray(rng.standard_normal((B, K, H, Dh)), jnp.bfloat16)
    k_bf = jnp.asarray(rng.standard_normal((B, S, Hkv, Dh)), jnp.bfloat16)
    v_bf = jnp.asarray(rng.standard_normal((B, S, Hkv, Dh)), jnp.bfloat16)
    k_i8 = jnp.asarray(rng.integers(-127, 127, (B, Hkv, S, Dh)), jnp.int8)
    v_i8 = jnp.asarray(rng.integers(-127, 127, (B, Hkv, S, Dh)), jnp.int8)
    ks = jnp.asarray(rng.random((B, Hkv, S)) * 0.01 + 0.001, jnp.float32)
    vs = jnp.asarray(rng.random((B, Hkv, S)) * 0.01 + 0.001, jnp.float32)
    mask = jnp.asarray(np.ones((B, S), bool))
    maskk = jnp.asarray(np.ones((B, K, S), bool))

    i8_bytes = 2 * B * Hkv * S * Dh + 2 * B * Hkv * S * 4
    bf_bytes = 2 * B * S * Hkv * Dh * 2
    print(f"shapes: B={B} H={H} Hkv={Hkv} Dh={Dh} S={S}; per-step KV "
          f"traffic int8 {i8_bytes/1e6:.0f} MB, bf16 {bf_bytes/1e6:.0f} MB; "
          f"{ITERS} in-loop iterations")

    def attn_body(attn_fn):
        # carry = (acc, q); feed acc back into q so iterations serialize.
        def body(i, carry):
            acc, q = carry
            out = attn_fn(q)
            acc = acc + out.astype(jnp.float32).mean()
            q = q + (acc * 1e-20).astype(q.dtype)
            return (acc, q)
        return body

    # int8 Pallas kernel across block sizes.
    for bs in (512, 1024, 2048, 4096):
        t = loop_time(
            attn_body(partial(
                decode_attention, k=k_i8, v=v_i8, mask=mask, scale=scale,
                k_scale=ks, v_scale=vs, block_s=bs,
            )),
            (jnp.float32(0), q0),
        )
        print(f"int8 pallas  block={bs:<4d}: {t*1e3:7.3f} ms/it  "
              f"{i8_bytes/t/1e9:6.1f} GB/s")

    # bf16 einsum reference (the stock decode path).
    def einsum_path(q):
        qg = q.reshape(B, Hkv, H // Hkv, Dh)
        logits = jnp.einsum("bhgd,bshd->bhgs", qg, k_bf).astype(jnp.float32) * scale
        logits = jnp.where(mask[:, None, None, :], logits, -1e30)
        p = jax.nn.softmax(logits, axis=-1).astype(v_bf.dtype)
        return jnp.einsum("bhgs,bshd->bhgd", p, v_bf).reshape(B, H, Dh)

    t = loop_time(attn_body(einsum_path), (jnp.float32(0), q0))
    print(f"bf16 einsum           : {t*1e3:7.3f} ms/it  {bf_bytes/t/1e9:6.1f} GB/s")

    # int8 einsum-with-dequant (the non-Pallas int8 fallback shape).
    def dequant_einsum(q):
        kd = (k_i8.astype(jnp.float32) * ks[..., None]).transpose(0, 2, 1, 3).astype(q.dtype)
        vd = (v_i8.astype(jnp.float32) * vs[..., None]).transpose(0, 2, 1, 3).astype(q.dtype)
        qg = q.reshape(B, Hkv, H // Hkv, Dh)
        logits = jnp.einsum("bhgd,bshd->bhgs", qg, kd).astype(jnp.float32) * scale
        p = jax.nn.softmax(jnp.where(mask[:, None, None, :], logits, -1e30), axis=-1)
        return jnp.einsum("bhgs,bshd->bhgd", p.astype(vd.dtype), vd).reshape(B, H, Dh)

    t = loop_time(attn_body(dequant_einsum), (jnp.float32(0), q0))
    print(f"int8 dequant einsum   : {t*1e3:7.3f} ms/it  {i8_bytes/t/1e9:6.1f} GB/s")

    # int8 chunk kernel (the fast-forward path).
    def chunk_body(bs):
        def body(i, carry):
            acc, qk = carry
            out = chunk_decode_attention(
                qk, k_i8, v_i8, maskk, scale, k_scale=ks, v_scale=vs,
                block_s=bs,
            )
            acc = acc + out.astype(jnp.float32).mean()
            qk = qk + (acc * 1e-20).astype(qk.dtype)
            return (acc, qk)
        return body

    for bs in (512, 2048, 4096):
        t = loop_time(chunk_body(bs), (jnp.float32(0), qk0))
        print(f"int8 chunk{K} block={bs:<4d}: {t*1e3:7.3f} ms/it  "
              f"{i8_bytes/t/1e9:6.1f} GB/s")

    # Cache-write paths (per decode step): bf16 = 2 dynamic updates;
    # int8 = quantize + transpose + 4 updates (k/v/scales).
    kn = jnp.asarray(rng.standard_normal((B, K, Hkv, Dh)), jnp.bfloat16)

    def bf16_write(i, carry):
        acc, k_cache, v_cache = carry
        fresh = kn + (acc * 1e-20).astype(kn.dtype)
        k_cache = jax.lax.dynamic_update_slice(k_cache, fresh, (0, 0, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(v_cache, fresh, (0, 0, 0, 0))
        return (acc + k_cache[0, 0, 0, 0].astype(jnp.float32), k_cache, v_cache)

    t = loop_time(bf16_write, (jnp.float32(0), k_bf, v_bf))
    print(f"bf16 cache write (K={K}) : {t*1e3:7.3f} ms/it")

    def int8_write(i, carry):
        acc, kc, vc, ksc, vsc = carry
        fresh = kn + (acc * 1e-20).astype(kn.dtype)
        kq, s = quantize_kv(fresh)
        kc = jax.lax.dynamic_update_slice(kc, kq.transpose(0, 2, 1, 3), (0, 0, 0, 0))
        vc = jax.lax.dynamic_update_slice(vc, kq.transpose(0, 2, 1, 3), (0, 0, 0, 0))
        ksc = jax.lax.dynamic_update_slice(ksc, s.transpose(0, 2, 1), (0, 0, 0))
        vsc = jax.lax.dynamic_update_slice(vsc, s.transpose(0, 2, 1), (0, 0, 0))
        return (acc + kc[0, 0, 0, 0].astype(jnp.float32), kc, vc, ksc, vsc)

    t = loop_time(int8_write, (jnp.float32(0), k_i8, v_i8, ks, vs))
    print(f"int8 cache write (K={K}) : {t*1e3:7.3f} ms/it")


if __name__ == "__main__":
    main()
